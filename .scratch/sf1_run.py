"""NDS-H SF1 power run on the real chip with out-of-core streaming:
lineitem (~770MB of columns) streams through the chunked executor;
results validate against the CPU oracle. VERDICT item 3 done criterion."""
import sys, time
sys.path.insert(0, "/root/repo")
from nds_tpu.utils.xla_cache import enable
enable()
import numpy as np
from nds_tpu.engine.chunked_exec import make_chunked_factory
from nds_tpu.engine.session import Session
from nds_tpu.io import table_cache
from nds_tpu.nds_h import streams
from nds_tpu.nds_h.schema import get_schemas
sys.path.insert(0, "/root/repo/tests")

tables = table_cache.load_tables("/root/repo/.bench_data/nds_h_sf1",
                                 get_schemas())
assert tables is not None

def mk(factory=None):
    s = Session.for_nds_h(factory)
    for t in tables.values():
        s.register_table(t)
    return s

dev = mk(make_chunked_factory(stream_bytes=256 << 20,
                              chunk_rows=1 << 21))
cpu = mk()
from test_device_engine import assert_frames_close  # noqa: E402

total_dev = total_cpu = 0.0
fails = []
for qn in range(1, 23):
    try:
        stmts = list(streams.statements(qn))
        t0 = time.perf_counter()
        g = None
        for s in stmts:
            r = dev.sql(s)
            g = r if r is not None else g
        t1 = time.perf_counter()
        e = None
        for s in stmts:
            r = cpu.sql(s)
            e = r if r is not None else e
        t2 = time.perf_counter()
        assert_frames_close(g.to_pandas(), e.to_pandas(), f"sf1-q{qn}")
        total_dev += t1 - t0
        total_cpu += t2 - t1
        print(f"sf1 q{qn}: dev {1000*(t1-t0):.0f} ms cpu "
              f"{1000*(t2-t1):.0f} ms MATCH", flush=True)
    except Exception as exc:
        fails.append(qn)
        print(f"sf1 q{qn}: FAIL {type(exc).__name__}: {str(exc)[:200]}",
              flush=True)
print(f"SF1 TOTAL dev {total_dev:.1f}s cpu {total_cpu:.1f}s "
      f"fails={fails}", flush=True)
