#!/bin/bash
# Chunked drive of the 99-template distributed differential tier:
# one pytest process per 9-template batch so compiled shard_map
# executables (GBs each on the virtual CPU mesh) never accumulate past
# a process boundary (the full-run process peaked at 130GB and OOMed).
set -u
mkdir -p .scratch/dist99
PASS=0; FAIL=0
for start in $(seq 0 9 98); do
  ids=""
  for q in $(python -c "
from nds_tpu.nds import streams
qs = streams.available_templates()[$start:$start+9]
print(' '.join(str(q) for q in qs))"); do
    ids="$ids tests/test_distributed.py::test_nds_distributed_matches_oracle[$q]"
  done
  timeout 7200 python -m pytest $ids -q > .scratch/dist99/batch_$start.log 2>&1
  code=$?
  p=$(grep -oE "[0-9]+ passed" .scratch/dist99/batch_$start.log | grep -oE "[0-9]+" | head -1)
  f=$(grep -oE "[0-9]+ failed" .scratch/dist99/batch_$start.log | grep -oE "[0-9]+" | head -1)
  PASS=$((PASS + ${p:-0})); FAIL=$((FAIL + ${f:-0}))
  echo "batch $start: exit=$code passed=${p:-0} failed=${f:-0} (total $PASS/$((PASS+FAIL)))"
done
echo "DIST99 DONE: $PASS passed, $FAIL failed"
