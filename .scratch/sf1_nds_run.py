"""NDS (TPC-DS) SF1 power run on the real chip with out-of-core
streaming: the big facts (store_sales ~2.9M rows, inventory ~11.7M,
catalog/web sales) stream through the chunked executor; every query
validates against the CPU oracle. VERDICT r3 "next" #4 done criterion.
Writes per-query wall-clocks to SF1_NDS.json (committed artifact).

Usage: python .scratch/sf1_nds_run.py [start_q] [stop_q]
"""
import json
import os
import sys
import time

sys.path.insert(0, "/root/repo")
from nds_tpu.utils.xla_cache import enable
enable()

from nds_tpu.engine.chunked_exec import make_chunked_factory
from nds_tpu.engine.session import Session
from nds_tpu.io import table_cache
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds import streams
from nds_tpu.nds.schema import get_schemas
sys.path.insert(0, "/root/repo/tests")

DATA = "/root/repo/.bench_data/nds_sf1"
OUT = "/root/repo/SF1_NDS.json"

schemas = get_schemas()
tables = table_cache.load_tables(DATA, schemas)
if tables is None:
    print("generating SF1 tables (cached thereafter)...", flush=True)
    from nds_tpu.datagen import tpcds
    tables = {t: from_arrays(t, schemas[t], tpcds.gen_table(t, 1.0))
              for t in schemas}
    table_cache.save_tables(DATA, tables)


def mk(factory=None):
    s = Session.for_nds(factory)
    for t in tables.values():
        s.register_table(t)
    return s


dev = mk(make_chunked_factory(stream_bytes=256 << 20,
                              chunk_rows=1 << 21))
cpu = mk()
from test_device_engine import assert_frames_close  # noqa: E402

bank = {}
if os.path.exists(OUT):
    bank = json.load(open(OUT)).get("queries", {})

qids = streams.available_templates()
lo = int(sys.argv[1]) if len(sys.argv) > 1 else 0
hi = int(sys.argv[2]) if len(sys.argv) > 2 else len(qids)
for qn in qids[lo:hi]:
    if str(qn) in bank and bank[str(qn)].get("status") == "MATCH":
        continue
    try:
        stmts = [s for s in streams.render_query(qn).split(";")
                 if s.strip()]
        t0 = time.perf_counter()
        gs = []
        for s in stmts:
            r = dev.sql(s)
            if r is not None:
                gs.append(r)
        t1 = time.perf_counter()
        es = []
        for s in stmts:
            r = cpu.sql(s)
            if r is not None:
                es.append(r)
        t2 = time.perf_counter()
        for g, e in zip(gs, es):
            assert_frames_close(g.to_pandas(), e.to_pandas(),
                                f"sf1-q{qn}")
        bank[str(qn)] = {"status": "MATCH",
                         "device_s": round(t1 - t0, 3),
                         "cpu_s": round(t2 - t1, 3)}
        print(f"sf1 nds q{qn}: dev {1000*(t1-t0):.0f} ms "
              f"cpu {1000*(t2-t1):.0f} ms MATCH", flush=True)
    except Exception as exc:  # noqa: BLE001
        bank[str(qn)] = {"status": "FAIL",
                         "error": f"{type(exc).__name__}: "
                                  f"{str(exc)[:200]}"}
        print(f"sf1 nds q{qn}: FAIL {type(exc).__name__}: "
              f"{str(exc)[:200]}", flush=True)
    done = [q for q, r in bank.items() if r.get("status") == "MATCH"]
    summary = {
        "suite": "nds", "scale_factor": 1.0,
        "stream_bytes": 256 << 20,
        "matched": len(done), "total": len(qids),
        "device_total_s": round(sum(bank[q]["device_s"]
                                    for q in done), 2),
        "cpu_total_s": round(sum(bank[q]["cpu_s"] for q in done), 2),
        "queries": bank,
    }
    with open(OUT + ".tmp", "w") as f:
        json.dump(summary, f, indent=1)
    os.replace(OUT + ".tmp", OUT)
print("done:", json.load(open(OUT))["matched"], "/", len(qids))
