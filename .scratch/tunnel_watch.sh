#!/bin/bash
# Round-5 watchdog: probe the TPU tunnel every 5 min; when it answers,
# launch bench.py (SF1 legs) plus ONE reverse-order compile warmer for
# the NDS leg (2 concurrent compile clients max — 3 wedged the remote
# compile service in round 4). Waits for the CPU-oracle banking job to
# finish first so the timed legs never share the single core.
cd /root/repo
while true; do
  if timeout 90 python -c "import jax; assert len(jax.devices())>=1 and jax.default_backend()!='cpu'" >/dev/null 2>&1; then
    echo "$(date -u) tunnel UP" >> .scratch/tunnel_watch.log
    for i in $(seq 90); do
      [ -f .scratch/cpu_bank_done ] && break
      pgrep -f bank_cpu.py >/dev/null || break
      sleep 60
    done
    echo "$(date -u) starting bench + warmer" >> .scratch/tunnel_watch.log
    nohup python .scratch/warm_nds.py nds 0 99 reverse \
        > .scratch/warm_r5.log 2>&1 &
    WARMER=$!
    nohup python bench.py > .scratch/bench_r5_run.log 2>&1
    echo "$(date -u) bench exited $?" >> .scratch/tunnel_watch.log
    kill $WARMER 2>/dev/null
    exit 0
  fi
  echo "$(date -u) tunnel down" >> .scratch/tunnel_watch.log
  sleep 300
done
