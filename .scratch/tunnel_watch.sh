#!/bin/bash
# Probe the TPU tunnel every 5 min; when it answers, relaunch bench.py
# (banked cpu times + persistent XLA cache make the restart cheap).
while true; do
  if timeout 90 python -c "import jax; assert jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u) tunnel UP - starting bench" >> .scratch/tunnel_watch.log
    nohup python bench.py > .scratch/bench_r4_run2.log 2>&1
    echo "$(date -u) bench exited $?" >> .scratch/tunnel_watch.log
    exit 0
  fi
  echo "$(date -u) tunnel down" >> .scratch/tunnel_watch.log
  sleep 300
done
