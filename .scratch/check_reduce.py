"""Quick differential check of filtered-scan survivor reduction:
forces REDUCE_MIN_ROWS=1 so tiny test tables reduce, runs NDS-H 22
queries + a sample of NDS queries device-vs-oracle on the CPU backend."""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "true")
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, "/root/repo")

from nds_tpu.engine import device_exec as dx
dx.DeviceExecutor.REDUCE_MIN_ROWS = 1  # force reduction everywhere

from nds_tpu.datagen import tpcds, tpch
from nds_tpu.engine.device_exec import make_device_factory
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds import streams as nds_streams
from nds_tpu.nds.schema import get_schemas as nds_schemas
from nds_tpu.nds_h import streams as h_streams
from nds_tpu.nds_h.schema import get_schemas as h_schemas

from tests.test_device_engine import assert_frames_close, run_query

SF = 0.01


def make_sessions(schemas_fn, gen, for_fn):
    schemas = schemas_fn()
    raw = {t: gen.gen_table(t, SF) for t in schemas}
    cpu = for_fn(None)
    dev = for_fn(make_device_factory())
    for t in schemas:
        ht = from_arrays(t, schemas[t], raw[t])
        cpu.register_table(ht)
        dev.register_table(ht)
    return cpu, dev


def check(tag, cpu, dev, stmts_fn, qns):
    bad = []
    for qn in qns:
        try:
            for s in stmts_fn(qn):
                rc = cpu.sql(s)
                rd = dev.sql(s)
                if rc is not None:
                    assert_frames_close(rd.to_pandas(), rc.to_pandas(), qn)
            print(f"{tag} q{qn}: OK", flush=True)
        except Exception as e:  # noqa: BLE001
            bad.append((qn, e))
            print(f"{tag} q{qn}: FAIL {type(e).__name__}: {e}", flush=True)
    return bad


def main():
    bad = []
    cpu, dev = make_sessions(h_schemas, tpch, Session.for_nds_h)
    bad += check("nds_h", cpu, dev, h_streams.statements, range(1, 23))
    qns = [int(a) for a in sys.argv[1:]] or [
        1, 4, 6, 7, 10, 13, 18, 25, 34, 37, 48, 68, 85, 91]
    cpu, dev = make_sessions(nds_schemas, tpcds, Session.for_nds)

    def nds_stmts(qn):
        return [s for s in nds_streams.render_query(qn).split(";")
                if s.strip()]

    bad += check("nds", cpu, dev, nds_stmts, qns)
    print("FAILURES:", len(bad))
    sys.exit(1 if bad else 0)


main()
