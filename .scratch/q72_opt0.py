"""q72 distributed differential on a 2-device virtual mesh: the
8-device shard_map compile of this widest-plan template exceeds host
RAM on the CPU backend (~130GB), a compile-memory limit, not a
sharding-semantics one — 2 devices still execute every collective."""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
flags = " ".join(f for f in flags.split()
                 if "host_platform_device_count" not in f)
os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo")
from nds_tpu.datagen import tpcds
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds import streams
from nds_tpu.nds.schema import get_schemas
from nds_tpu.parallel.dist_exec import make_distributed_factory
sys.path.insert(0, "/root/repo/tests")
from test_device_engine import assert_frames_close

SF = 0.01
schemas = get_schemas()
cpu = Session.for_nds()
dist = Session.for_nds(make_distributed_factory(n_devices=8,
                                                shard_threshold=1000))
for t in schemas:
    raw = tpcds.gen_table(t, SF)
    cpu.register_table(from_arrays(t, schemas[t], raw))
    dist.register_table(from_arrays(t, schemas[t], raw))
for part, stmt in enumerate([s for s in streams.render_query(72).split(";")
                             if s.strip()], 1):
    e = cpu.sql(stmt)
    g = dist.sql(stmt)
    if e is None or g is None:
        continue
    assert_frames_close(g.to_pandas(), e.to_pandas(), f"q72_part{part}")
    print(f"q72 part{part}: {e.nrows} rows MATCH", flush=True)
print("q72 DISTRIBUTED OK at SF0.01 x 8 devices opt0", flush=True)
