import sys, time
sys.path.insert(0, "/root/repo")
from nds_tpu.utils.xla_cache import enable
enable()
import jax
from nds_tpu.engine.device_exec import DeviceExecutor
from nds_tpu.engine.session import Session
from nds_tpu.io import table_cache
from nds_tpu.nds_h import streams
from nds_tpu.nds_h.schema import get_schemas

tables = table_cache.load_tables("/root/repo/.bench_data/nds_h_sf0.3",
                                 get_schemas())
sess = Session.for_nds_h(lambda t: ex)
ex = DeviceExecutor(tables)
for t in tables.values():
    sess.register_table(t)

qn = int(sys.argv[1]) if len(sys.argv) > 1 else 16
sql = list(streams.statements(qn))
for s in sql:
    sess.sql(s)  # warm
for trial in range(3):
    t0 = time.perf_counter()
    for s in sql:
        r = sess.sql(s)
    dt = (time.perf_counter() - t0) * 1000
    print(f"q{qn} trial{trial}: {dt:.0f} ms  timings={ex.last_timings}",
          flush=True)
