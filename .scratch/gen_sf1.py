import os, sys, time
sys.path.insert(0, "/root/repo")
from nds_tpu.datagen import tpch
from nds_tpu.io import table_cache
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h.schema import get_schemas
schemas = get_schemas()
out = "/root/repo/.bench_data/nds_h_sf1"
t0 = time.time()
tables = {}
for t in schemas:
    tables[t] = from_arrays(t, schemas[t], tpch.gen_table(t, 1.0))
    print(t, tables[t].nrows, f"{time.time()-t0:.0f}s", flush=True)
table_cache.save_tables(out, tables)
print("saved", out, flush=True)
