"""Micro-bench engine primitive patterns at q21 scale on the chip.

block_until_ready is a no-op over the axon tunnel; every timed iteration
ends with a device_get of a scalar reduction to force completion. The
'noop' row measures the RTT floor to subtract.
"""
import sys
import time
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

jax.config.update("jax_enable_x64", True)

N = 1_800_000
rng = np.random.default_rng(0)
keys32 = jnp.asarray(rng.integers(0, 450_000, N, dtype=np.int32))
keys64 = keys32.astype(jnp.int64)
probe32 = jnp.asarray(rng.integers(0, 450_000, N, dtype=np.int32))
probe64 = probe32.astype(jnp.int64)
iota32 = jnp.arange(N, dtype=jnp.int32)
idx = probe32 % N


def bench(name, fn, *args):
    # reduce result(s) to one scalar inside the jit so the device_get
    # transfer is tiny; the get forces execution over the tunnel
    def wrapped(*a):
        r = fn(*a)
        leaves = jax.tree_util.tree_leaves(r)
        acc = jnp.zeros((), jnp.int64)
        for x in leaves:
            if jnp.issubdtype(x.dtype, jnp.floating):
                acc = acc + jnp.sum(x).astype(jnp.int64)
            else:
                acc = acc + jnp.sum(x.astype(jnp.int64))
        return acc
    f = jax.jit(wrapped)
    jax.device_get(f(*args))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.device_get(f(*args))
        ts.append(time.perf_counter() - t0)
    print(f"{name:46s} {min(ts)*1000:8.2f} ms", flush=True)


bench("noop (RTT floor)", lambda k: k[:8], keys32)
bench("sort i32", lambda k: jnp.sort(k), keys32)
bench("sort i64", lambda k: jnp.sort(k), keys64)
bench("sort [i32,i32] 1key stable", lambda k, i: lax.sort([k, i], num_keys=1, is_stable=True), keys32, iota32)
bench("sort [i32,i32] 2key", lambda k, i: lax.sort([k, i], num_keys=2), keys32, probe32)
bench("sort [i32]x5 4key stable", lambda k, i: lax.sort([k, i, k, i, iota32], num_keys=4, is_stable=True), keys32, probe32)
ks32 = jnp.sort(keys32)
ks64 = jnp.sort(keys64)
bench("searchsorted i32 scan(default)", lambda s, p: jnp.searchsorted(s, p), ks32, probe32)
bench("searchsorted i64 scan(default)", lambda s, p: jnp.searchsorted(s, p), ks64, probe64)
bench("searchsorted i32 sort-method", lambda s, p: jnp.searchsorted(s, p, method="sort"), ks32, probe32)
bench("searchsorted i64 sort-method", lambda s, p: jnp.searchsorted(s, p, method="sort"), ks64, probe64)
bench("gather i32 (take)", lambda a, i: jnp.take(a, i), keys32, idx)
bench("gather i64 (take)", lambda a, i: jnp.take(a, i), keys64, idx)
bench("gather i32 x8 cols", lambda a, i: [jnp.take(a + j, i) for j in range(8)], keys32, idx)
bench("cumsum i32->i64", lambda a: jnp.cumsum(a.astype(jnp.int64)), keys32)
bench("cumsum i32->i32", lambda a: jnp.cumsum(a), keys32)
bench("associative_scan add i64", lambda a: lax.associative_scan(jnp.add, a.astype(jnp.int64)), keys32)
bench("scatter .at[].set i32", lambda a, i: jnp.zeros(N, jnp.int32).at[i].set(a), keys32, idx)
bench("scatter .at[].max i32", lambda a, i: jnp.zeros(N, jnp.int32).at[i].max(a), keys32, idx)
bench("segment_sum i64 sorted", lambda a, g: jax.ops.segment_sum(a.astype(jnp.int64), g, num_segments=N, indices_are_sorted=True), keys32, jnp.sort(idx))
bench("segment_sum i32 sorted", lambda a, g: jax.ops.segment_sum(a, g, num_segments=N, indices_are_sorted=True), keys32, jnp.sort(idx))
bench("elementwise x5", lambda a, b: jnp.where(a > b, a * 2 + b, a - b) + jnp.where(b > 0, a, b), keys32, probe32)
bench("mul i64", lambda a, b: a.astype(jnp.int64) * b.astype(jnp.int64), keys32, probe32)
bench("mul f64", lambda a, b: a.astype(jnp.float64) * b.astype(jnp.float64), keys32, probe32)
