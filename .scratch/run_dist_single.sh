#!/bin/bash
# One pytest process per template (for the batches whose 9-template
# processes OOMed: the q11/q64 YoY family compiles are tens of GB each)
set -u
for q in "$@"; do
  timeout 7200 python -m pytest "tests/test_distributed.py::test_nds_distributed_matches_oracle[$q]" -q > .scratch/dist99/single_$q.log 2>&1
  code=$?
  res=$(tail -1 .scratch/dist99/single_$q.log | tr -d '\n')
  echo "q$q: exit=$code $res"
done
