"""Sweep all 99 NDS templates through the distributed executor on the
virtual 8-device CPU mesh; report per-query wall time and mismatches."""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, "/root/repo")

import numpy as np
import pandas as pd

from nds_tpu.datagen import tpcds
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds import streams
from nds_tpu.nds.schema import get_schemas
from nds_tpu.parallel.dist_exec import make_distributed_factory

sys.path.insert(0, "/root/repo/tests")
from test_device_engine import assert_frames_close  # noqa: E402

SF = 0.01
THRESHOLD = 1000

schemas = get_schemas()
raw = {t: tpcds.gen_table(t, SF) for t in schemas}
cpu = Session.for_nds()
dist = Session.for_nds(make_distributed_factory(
    n_devices=8, shard_threshold=THRESHOLD))
for t in schemas:
    cpu.register_table(from_arrays(t, schemas[t], raw[t]))
    dist.register_table(from_arrays(t, schemas[t], raw[t]))

qids = streams.available_templates()
start = int(sys.argv[1]) if len(sys.argv) > 1 else 0
stop = int(sys.argv[2]) if len(sys.argv) > 2 else len(qids)
fails = []
for qn in qids[start:stop]:
    t0 = time.perf_counter()
    try:
        sql = streams.render_query(qn)
        stmts = [s for s in sql.split(";") if s.strip()]
        exps = [cpu.sql(s) for s in stmts]
        t1 = time.perf_counter()
        gots = [dist.sql(s) for s in stmts]
        t2 = time.perf_counter()
        for part, (e, g) in enumerate(zip(exps, gots), 1):
            if e is None or g is None:
                continue
            assert_frames_close(g.to_pandas(), e.to_pandas(),
                                f"{qn}_part{part}")
        print(f"q{qn}: OK cpu={t1-t0:.1f}s dist={t2-t1:.1f}s", flush=True)
    except Exception as exc:  # noqa: BLE001
        fails.append(qn)
        print(f"q{qn}: FAIL {type(exc).__name__}: {str(exc)[:200]}",
              flush=True)
print("FAILS:", fails, flush=True)
