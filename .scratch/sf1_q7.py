import sys, time
sys.path.insert(0, "/root/repo"); sys.path.insert(0, "/root/repo/tests")
from nds_tpu.utils.xla_cache import enable
enable()
from nds_tpu.engine.chunked_exec import make_chunked_factory
from nds_tpu.engine.session import Session
from nds_tpu.io import table_cache
from nds_tpu.nds_h import streams
from nds_tpu.nds_h.schema import get_schemas
from test_device_engine import assert_frames_close

tables = table_cache.load_tables("/root/repo/.bench_data/nds_h_sf1", get_schemas())
def mk(f=None):
    s = Session.for_nds_h(f)
    for t in tables.values():
        s.register_table(t)
    return s
dev = mk(make_chunked_factory(stream_bytes=256 << 20, chunk_rows=1 << 21))
cpu = mk()
for attempt in range(3):
    try:
        t0 = time.perf_counter()
        g = dev.sql(streams.render_query(7))
        t1 = time.perf_counter()
        e = cpu.sql(streams.render_query(7))
        assert_frames_close(g.to_pandas(), e.to_pandas(), "sf1-q7")
        print(f"sf1 q7: dev {1000*(t1-t0):.0f} ms MATCH", flush=True)
        break
    except Exception as exc:
        print(f"sf1 q7 attempt {attempt}: {type(exc).__name__}: {str(exc)[:150]}", flush=True)
