"""Pre-bank the CPU-oracle denominator for bench.py's legs.

Run with the tunnel down (pure CPU): generates/loads SF data, times the
CPU oracle per unit, and saves incrementally to bench's cpu bank format
so the driver's device run only pays the device leg.

Usage: NDS_TPU_PLATFORM=cpu python .scratch/bank_cpu.py nds_h nds
"""
import os
import sys
import time

os.environ.setdefault("NDS_TPU_PLATFORM", "cpu")  # never touch the tunnel
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402
from nds_tpu.engine.session import Session  # noqa: E402

for leg in sys.argv[1:]:
    tables = bench._load_or_gen(leg)
    units = bench._leg_units(leg)
    mk = Session.for_nds_h if leg == "nds_h" else Session.for_nds
    cpu = mk()
    for t in tables.values():
        cpu.register_table(t)
    times = bench._load_cpu_bank(leg, tables)
    print(f"[bank_cpu] {leg}: {len(times)} already banked, "
          f"{len(units)} units total", flush=True)
    for qn, stmts in units:
        if stmts is None or qn in times:
            continue
        try:
            t0 = time.perf_counter()
            for s in stmts:
                cpu.sql(s)
            times[qn] = time.perf_counter() - t0
        except Exception as exc:  # noqa: BLE001
            print(f"[bank_cpu] {leg} q{qn}: FAILED "
                  f"{type(exc).__name__}: {exc}", flush=True)
            continue
        bench._save_cpu_bank(leg, tables, times)
        print(f"[bank_cpu] {leg} q{qn}: {times[qn]*1000:.0f} ms", flush=True)
    print(f"[bank_cpu] {leg} done: {len(times)}/{len(units)}", flush=True)

open(os.path.join(os.path.dirname(__file__), "cpu_bank_done"), "w").write(
    str(time.time()))
print("[bank_cpu] all legs done", flush=True)
