"""Cache warmer: compile NDS (and optionally NDS-H) bench programs into
the persistent XLA cache WITHOUT touching device memory — lowering from
ShapeDtypeStruct avatars, so N warmers can run in parallel against the
remote compile service while bench.py executes.

Usage: python warm_nds.py <leg> <start_idx> <stop_idx> [reverse]
"""
import os
import sys
import time

sys.path.insert(0, "/root/repo")

leg = sys.argv[1]
start, stop = int(sys.argv[2]), int(sys.argv[3])
rev = len(sys.argv) > 4 and sys.argv[4] == "reverse"

from nds_tpu.utils.xla_cache import enable as enable_xla_cache

enable_xla_cache()

import jax
import numpy as np

from nds_tpu.engine.device_exec import DeviceExecutor
from nds_tpu.engine.session import Session
from nds_tpu.io import table_cache
from nds_tpu.sql import plan as P

if leg == "nds":
    from nds_tpu.nds import streams
    from nds_tpu.nds.schema import get_schemas
    qids = streams.available_templates()
    mk = Session.for_nds
    data_dir = os.environ.get(
        "WARM_DATA", "/root/repo/.bench_data/nds_sf1")
else:
    from nds_tpu.nds_h import streams
    from nds_tpu.nds_h.schema import get_schemas
    qids = list(range(1, 23))
    mk = Session.for_nds_h
    data_dir = os.environ.get(
        "WARM_DATA", "/root/repo/.bench_data/nds_h_sf1")

tables = table_cache.load_tables(data_dir, get_schemas())
assert tables is not None, data_dir
sess = mk()
for t in tables.values():
    sess.register_table(t)
ex = DeviceExecutor(tables)

qs = qids[start:stop]
if os.environ.get("QLIST"):
    qs = [int(x) for x in os.environ["QLIST"].split(",")]
if rev:
    qs = list(reversed(qs))


def specs_for(planned):
    """Avatar specs mirroring DeviceExecutor._collect_buffers: reduced
    scans get reduced-prefix keys at reduced pow2 capacity."""
    out = {}
    roots = [planned.root] + list(planned.scalar_subplans)
    for root in roots:
        for node in P.walk_plan(root):
            if not isinstance(node, P.Scan):
                continue
            t = tables[node.table]
            rv = ex.scan_view(node)
            for name, _dt in node.output:
                col = t.columns[name]
                if rv is not None:
                    key = f"{rv.prefix}.{name}"
                    shape = (rv.capacity,)
                else:
                    key = f"{node.table}.{name}"
                    shape = col.values.shape
                out[key] = jax.ShapeDtypeStruct(
                    shape, col.values.dtype)
                if col.null_mask is not None:
                    out[key + "#v"] = jax.ShapeDtypeStruct(
                        shape, np.dtype(bool))
    return out


for qn in qs:
    t0 = time.time()
    try:
        sql = streams.render_query(qn)
        if leg == "nds_h":
            stmts = list(streams.statements(qn, sql))
        else:
            stmts = [s for s in sql.split(";") if s.strip()]
        for si, s in enumerate(stmts):
            planned = sess.plan(s)
            if planned is None or getattr(planned, "root", None) is None:
                continue
            jitted, _side = ex._compile(planned)
            specs = specs_for(planned)
            for attempt in range(3):
                try:
                    jitted.lower(specs).compile()
                    break
                except Exception as exc:  # noqa: BLE001
                    if attempt == 2 or "remote_compile" not in str(exc):
                        raise
                    print(f"  q{qn} stmt{si}: transient, retry",
                          flush=True)
        print(f"warm {leg} q{qn}: {time.time()-t0:.0f}s", flush=True)
    except Exception as exc:  # noqa: BLE001
        print(f"warm {leg} q{qn}: FAIL {type(exc).__name__}: "
              f"{str(exc)[:150]}", flush=True)
