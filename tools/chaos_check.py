"""Deterministic chaos gate: a tiny NDS power stream under injected
faults, asserted end-to-end.

tier-1 (via tools/static_checks.py) runs a 3-query NDS power stream on
the CPU backend with a FIXED fault schedule — one transient
device.execute OOM (must be retried and succeed, ``retries=1``,
status ``Completed``) and one deterministic plan fault (must fail
FAST: one attempt, ``gave_up_reason=deterministic``) — then checks the
per-query JSON summaries, the TimeLog CSV (the stream never aborts),
the resilience metrics counters, and the PhaseJournal resume
round-trip. The schedule is seeded, so every CI run replays the exact
same failure sequence; a regression in classification, retry
accounting, or journaling fails here before any differential tier
spins up a device.

Two placement/ladder scenarios (engine/scheduler.py) ride on the same
generated data:

- **ladder** — a 3-query power stream on the tpu backend with device
  OOM injected at BOTH device-side placements (scoped to the executor
  class names, so the CPU floor stays healthy): every query must walk
  the full degradation ladder (device -> chunked -> cpu), complete
  with ``reschedules: 2`` recorded in its summary, and produce result
  rows IDENTICAL to a clean cpu-backend run of the same stream.

- **consensus** — the same stream on the distributed backend (8-device
  virtual mesh) with OOM injected at the sharded placement: the first
  queries reschedule through a consensus vote (degenerate one-rank
  world — the same code path real multi-process runs take), the
  reschedule streak demotes the stream's starting placement, the run
  completes degraded with no deadlock, and
  ``placement_consensus_total`` / ``placement_demotions_total`` move.

One plan-cache scenario (nds_tpu/cache/; README "Plan cache") rides on
the same generated data:

- **cache-corruption** — byte-flip every persisted AOT payload between
  two identical device-placement streams: the second run must treat
  every corrupt entry as a warned miss (``compile_cache_errors_total``
  moves, zero hits, fresh compiles), complete every query with
  ``retries=0`` and rows identical to the cold run, and re-persist —
  a third run serves fully warm with ZERO compiles.

Two watchdog/integrity scenarios ride on the same generated data:

- **hang** — a 4-stream SUPERVISED subprocess throughput round with a
  ``stream.query:hang`` injected into one stream: the child watchdog
  must catch the stall within 2x ``stall_s`` (exit ``EXIT_STALLED``,
  stall report dumped), the supervisor must restart the stream ONCE
  from its last completed query, and the round must complete with the
  stall + restart recorded in ``throughput_summary.json``.

- **corrupt** — an ``io.read:corrupt`` byte-flip in one raw chunk with
  digest verification on: the warehouse load must fail FAST with
  ``CorruptArtifact`` naming the file and both digests, zero retries,
  and a Failed ``load_warehouse`` BenchReport on disk. Runs LAST — the
  flip really mutates the shared raw data.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCALE = 0.01
TEMPLATES = [96, 7, 93]
# query7 dies once with an injected device OOM (transient: retried);
# query93 dies at plan time (deterministic: never retried)
SCHEDULE = "device.execute:oom@query7,plan:deterministic@query93"


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def run_chaos_stream(workdir: str) -> int:
    from nds_tpu.nds import gen_data, streams
    from nds_tpu.nds.power import SUITE
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.resilience import faults
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    from nds_tpu.utils.timelog import TimeLog

    raw = os.path.join(workdir, "raw")
    sdir = os.path.join(workdir, "streams")
    jsons = os.path.join(workdir, "json")
    tlog = os.path.join(workdir, "time.csv")
    gen_data.generate_data_local(SCALE, 2, raw, workers=2)
    streams.generate_query_streams(sdir, 1, templates=TEMPLATES)

    cfg = EngineConfig(overrides={
        "engine.backend": "cpu",
        "engine.retry.base_delay_s": "0.01",
        "engine.retry.max_attempts": "3",
    })
    before = obs_metrics.snapshot()
    plan = faults.install(SCHEDULE, seed=7)
    try:
        failures = power_core.run_query_stream(
            SUITE, raw, os.path.join(sdir, "query_0.sql"), tlog,
            config=cfg, input_format="raw",
            json_summary_folder=jsons)
    finally:
        faults.clear()
    delta = obs_metrics.delta(before, obs_metrics.snapshot())
    counters = delta.get("counters", {})

    if failures != 1:
        return _fail(f"expected exactly the deterministic failure, "
                     f"got {failures}")
    summaries = _stream_summaries(jsons)
    q96, q7, q93 = (summaries.get(f"query{n}") for n in TEMPLATES)
    if not (q96 and q7 and q93):
        return _fail(f"missing summaries: {sorted(summaries)}")
    if q96["queryStatus"] != ["Completed"] or q96.get("retries") != 0:
        return _fail(f"query96 should complete untouched: {q96}")
    if q7["queryStatus"] != ["Completed"] or q7.get("retries") != 1:
        return _fail(f"query7 should complete after ONE retry: "
                     f"status={q7['queryStatus']} "
                     f"retries={q7.get('retries')}")
    if (q93["queryStatus"] != ["Failed"]
            or q93.get("gave_up_reason") != "deterministic"
            or q93.get("retries") != 0):
        return _fail(f"query93 should fail fast without retry: {q93}")
    if "injected deterministic fault" not in " ".join(q93["exceptions"]):
        return _fail(f"query93 exception text lost: {q93['exceptions']}")
    # the stream never aborts: every query has a TimeLog row
    names = [q for _a, q, _ms in TimeLog.read(tlog)]
    for n in TEMPLATES:
        if f"query{n}" not in names:
            return _fail(f"query{n} missing from TimeLog {names}")
    if counters.get("query_retries_total") != 1:
        return _fail(f"query_retries_total delta: {counters}")
    if counters.get("faults_injected_total") != 2:
        return _fail(f"faults_injected_total delta: {counters}")
    fired = {(sp.site, sp.fired) for sp in plan.specs}
    if fired != {("device.execute", 1), ("plan", 1)}:
        return _fail(f"unexpected firing counts {fired}")
    print("OK: chaos stream (1 transient retried, 1 deterministic "
          "fail-fast, stream completed)")
    return 0


def run_journal_check(workdir: str) -> int:
    from nds_tpu.resilience.journal import (
        JournalMismatch, PhaseJournal, config_digest,
    )
    path = os.path.join(workdir, "bench_state.json")
    digest = config_digest({"scale_factor": 0.01, "backend": "cpu"})
    j = PhaseJournal(path, digest)
    j.reset()
    j.complete("load_test", load_time_s=12.5, rngseed=42)
    j.complete("power_test", power_time_s=3.25)
    # a fresh journal object (the resumed process) replays the state
    j2 = PhaseJournal(path, digest)
    if not j2.load():
        return _fail("journal did not persist")
    if not (j2.done("load_test") and j2.done("power_test")):
        return _fail(f"phases lost: {j2.state}")
    if j2.done("throughput_1"):
        return _fail("phantom phase in journal")
    if j2.timings("load_test") != {"load_time_s": 12.5, "rngseed": 42}:
        return _fail(f"timings drifted: {j2.timings('load_test')}")
    # a different config must refuse to resume (digest guard)
    j3 = PhaseJournal(path, config_digest({"scale_factor": 3000}))
    try:
        j3.load()
    except JournalMismatch:
        pass
    else:
        return _fail("journal accepted a mismatched config digest")
    print("OK: phase journal round-trip + config-digest guard")
    return 0


def _stream_summaries(jsons: str) -> dict:
    """BenchReport summaries in a run dir — failed queries drop
    flight-recorder dumps (obs/fleet.py) next to them, so only files
    with the summary keys count."""
    out = {}
    for f in os.listdir(jsons):
        with open(os.path.join(jsons, f)) as fh:
            s = json.load(fh)
        if isinstance(s, dict) and "query" in s and "queryStatus" in s:
            out[s["query"]] = s
    return out


def run_ladder_stream(workdir: str) -> int:
    """Injected device OOM at every device-side placement: each query
    walks the FULL ladder (device -> chunked -> cpu), completes, and
    its rows match a clean CPU run bit-for-bit."""
    from nds_tpu.io.result_io import read_result
    from nds_tpu.nds.power import SUITE
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.resilience import faults
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig

    raw = os.path.join(workdir, "raw")
    sdir = os.path.join(workdir, "streams")
    stream = os.path.join(sdir, "query_0.sql")

    # clean reference rows: the same stream, cpu backend, no faults
    clean_out = os.path.join(workdir, "ladder_clean")
    power_core.run_query_stream(
        SUITE, raw, stream, os.path.join(workdir, "ladder_clean.csv"),
        config=EngineConfig(overrides={"engine.backend": "cpu"}),
        input_format="raw", output_prefix=clean_out)

    jsons = os.path.join(workdir, "json_ladder")
    chaos_out = os.path.join(workdir, "ladder_chaos")
    cfg = EngineConfig(overrides={
        "engine.backend": "tpu",
        "engine.retry.base_delay_s": "0.01",
        # keep the sticky demotion OUT of this scenario: every query
        # must start at the top and walk the whole ladder itself
        "engine.placement.demote_after": "99",
    })
    before = obs_metrics.snapshot()
    # scope by executor CLASS: the device and chunked placements die
    # with OOM on every attempt, the CPU floor never fires. The
    # chunked rung streams for real now (the scheduler lowers the
    # stream threshold on relief entries), so its dispatches run in
    # the phase A/B sub-executors — fail those too or the ladder
    # (correctly!) stops at chunked
    faults.install("device.execute:oom*99@DeviceExecutor,"
                   "device.execute:oom*99@ChunkedExecutor,"
                   "device.execute:oom*99@_PhaseBExecutor,"
                   "device.execute:oom*99@_PartialAggExecutor", seed=7)
    try:
        power_core.run_query_stream(
            SUITE, raw, stream,
            os.path.join(workdir, "ladder_time.csv"), config=cfg,
            input_format="raw", json_summary_folder=jsons,
            output_prefix=chaos_out)
    finally:
        faults.clear()
    # run_query_stream counts CompletedWithTaskFailures as non-success
    # (the chunked rung's internal chunk-halving notifies the
    # collector), so the gate keys on per-query statuses: every query
    # must COMPLETE — with or without recovered task failures
    sums = _stream_summaries(jsons)
    for n in TEMPLATES:
        s = sums.get(f"query{n}")
        if not s:
            return _fail(f"query{n} summary missing: {sorted(sums)}")
        if s["queryStatus"][-1] not in ("Completed",
                                        "CompletedWithTaskFailures"):
            return _fail(f"query{n} did not complete: "
                         f"{s['queryStatus']}")
        if s.get("placement") != "cpu" or s.get("reschedules") != 2:
            return _fail(
                f"query{n} should land on cpu after 2 reschedules: "
                f"placement={s.get('placement')} "
                f"reschedules={s.get('reschedules')}")
        if s.get("ladder") != ["device", "chunked", "cpu"]:
            return _fail(f"query{n} ladder wrong: {s.get('ladder')}")
    # correctness across the whole walk: identical rows to the clean
    # CPU run, query by query
    for n in TEMPLATES:
        a = read_result(os.path.join(clean_out, f"query{n}"))
        b = read_result(os.path.join(chaos_out, f"query{n}"))
        if not a.equals(b):
            return _fail(f"query{n} rows diverged from the clean CPU "
                         f"run after the ladder walk")
    delta = obs_metrics.delta(before, obs_metrics.snapshot())
    counters = delta.get("counters", {})
    if counters.get("query_reschedules_total", 0) < 2 * len(TEMPLATES):
        return _fail(f"query_reschedules_total delta: {counters}")
    print("OK: ladder stream (device OOM walked device->chunked->cpu "
          "per query, all completed, rows identical to clean CPU run)")
    return 0


def run_consensus_demotion(workdir: str) -> int:
    """Virtual-mesh consensus demotion: sharded-placement OOM
    reschedules through the consensus vote, the stream's starting
    placement demotes (all ranks together — degenerate 1-rank world
    here, same code path as a real pod), and the run completes
    degraded without deadlock."""
    from nds_tpu.nds.power import SUITE
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.resilience import faults
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig

    raw = os.path.join(workdir, "raw")
    stream = os.path.join(workdir, "streams", "query_0.sql")
    jsons = os.path.join(workdir, "json_consensus")
    cfg = EngineConfig(overrides={
        "engine.backend": "distributed",
        "engine.retry.base_delay_s": "0.01",
        "engine.placement.demote_after": "2",
    })
    before = obs_metrics.snapshot()
    faults.install("device.execute:oom*99@DistributedExecutor", seed=7)
    try:
        failures = power_core.run_query_stream(
            SUITE, raw, stream,
            os.path.join(workdir, "consensus_time.csv"), config=cfg,
            input_format="raw", json_summary_folder=jsons)
    finally:
        faults.clear()
    if failures != 0:
        return _fail(f"consensus stream should complete degraded, "
                     f"{failures} failed")
    sums = _stream_summaries(jsons)
    walked = [s for s in sums.values() if s.get("reschedules")]
    if not walked:
        return _fail(f"no query rescheduled off the sharded "
                     f"placement: { {q: s.get('placement') for q, s in sums.items()} }")
    for s in walked:
        if s.get("placement") == "sharded":
            return _fail(f"{s['query']} still reports the sharded "
                         f"placement after rescheduling: {s}")
    # after demote_after rescheduled queries the START demotes: the
    # last query must begin off-sharded with no ladder walk of its own
    last = sums.get(f"query{TEMPLATES[-1]}")
    if not last or last.get("reschedules") != 0 \
            or last.get("placement") == "sharded":
        return _fail(f"stream start should be demoted by the streak: "
                     f"{last}")
    delta = obs_metrics.delta(before, obs_metrics.snapshot())
    counters = delta.get("counters", {})
    if not counters.get("placement_consensus_total"):
        return _fail(f"placement_consensus_total delta: {counters}")
    if counters.get("placement_demotions_total") != 1:
        return _fail(f"placement_demotions_total delta: {counters}")
    print("OK: consensus demotion (sharded OOM rescheduled via "
          "consensus, stream start demoted, run completed degraded, "
          "no deadlock)")
    return 0


def run_cache_corruption(workdir: str) -> int:
    """Byte-flip every persisted plan-cache payload between two runs of
    the same stream: the second run must degrade every corrupt entry to
    a warned fresh compile (``compile_cache_errors_total`` moves, zero
    hits), complete every query with ``retries=0`` and rows identical
    to the cold run, quarantine the bad entries, and re-persist fresh
    ones — a third run serves fully warm with zero compiles."""
    from nds_tpu import cache as plan_cache
    from nds_tpu.cache.store import PAYLOAD_PREFIX
    from nds_tpu.io.result_io import read_result
    from nds_tpu.nds.power import SUITE
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig

    raw = os.path.join(workdir, "raw")
    stream = os.path.join(workdir, "streams", "query_0.sql")
    cache_dir = os.path.join(workdir, "plan_cache")

    def _cfg():
        # force the device placement so every query compiles through
        # the cache (the cost model may otherwise pick the cacheless
        # cpu rung for tiny inputs)
        return EngineConfig(overrides={
            "engine.backend": "tpu",
            "engine.placement.force": "device",
            "cache.dir": cache_dir,
        })

    def _one_run(tag: str):
        jsons = os.path.join(workdir, f"json_cache_{tag}")
        out = os.path.join(workdir, f"cache_rows_{tag}")
        before = obs_metrics.snapshot()
        failures = power_core.run_query_stream(
            SUITE, raw, stream,
            os.path.join(workdir, f"cache_{tag}.csv"), config=_cfg(),
            input_format="raw", json_summary_folder=jsons,
            output_prefix=out)
        delta = obs_metrics.delta(before, obs_metrics.snapshot())
        return failures, _stream_summaries(jsons), \
            delta.get("counters", {}), out

    try:
        fail_cold, _sums, cold, cold_out = _one_run("cold")
        if fail_cold:
            return _fail(f"cold cache run failed {fail_cold} queries")
        if not cold.get("compile_cache_bytes_written_total"):
            return _fail(f"cold run persisted nothing: {cold}")

        # flip one byte in EVERY payload: every later consult must see
        # the sha256 mismatch
        flipped = 0
        for root, _dirs, files in os.walk(cache_dir):
            for f in files:
                if not f.startswith(PAYLOAD_PREFIX) \
                        or f.endswith(".tmp"):
                    continue
                p = os.path.join(root, f)
                with open(p, "r+b") as fh:
                    fh.seek(137)
                    b = fh.read(1)
                    fh.seek(137)
                    fh.write(bytes([b[0] ^ 0xFF]))
                flipped += 1
        if not flipped:
            return _fail("no cache payloads found to corrupt")

        fail_cor, sums, cor, cor_out = _one_run("corrupt")
        if fail_cor:
            return _fail(f"corrupt cache must NEVER fail a query: "
                         f"{fail_cor} failed")
        for q, s in sums.items():
            if s["queryStatus"][-1] != "Completed" \
                    or s.get("retries") != 0:
                return _fail(f"{q} should complete with retries=0 "
                             f"despite the corrupt cache: "
                             f"status={s['queryStatus']} "
                             f"retries={s.get('retries')}")
        if not cor.get("compile_cache_errors_total"):
            return _fail(f"corruption must warn via "
                         f"compile_cache_errors_total: {cor}")
        if cor.get("compile_cache_hits_total"):
            return _fail(f"a flipped payload must never hit: {cor}")
        if not cor.get("compiles_total"):
            return _fail(f"corrupt entries must recompile fresh: {cor}")
        for n in TEMPLATES:
            a = read_result(os.path.join(cold_out, f"query{n}"))
            b = read_result(os.path.join(cor_out, f"query{n}"))
            if not a.equals(b):
                return _fail(f"query{n} rows diverged after the "
                             f"corrupt-cache recompile")

        # recovery: the fresh compiles re-persisted; a third run is
        # fully warm (0 compiles) and the store verifies clean
        fail_warm, _sums, warm, _out = _one_run("warm")
        if fail_warm:
            return _fail(f"warm rerun failed {fail_warm} queries")
        if warm.get("compiles_total") or warm.get("recompiles_total"):
            return _fail(f"warm rerun should compile NOTHING: {warm}")
        if not warm.get("compile_cache_hits_total"):
            return _fail(f"warm rerun should serve from cache: {warm}")
        store = plan_cache.PlanCache(cache_dir, readonly=True)
        bad = store.verify()
        if bad:
            return _fail(f"re-persisted store should verify clean: "
                         f"{bad}")
    finally:
        plan_cache.reset()
    print("OK: cache corruption (byte-flipped entries warned + "
          "recompiled fresh, queries Completed retries=0 with "
          "identical rows, store re-persisted and fully warm)")
    return 0


def run_watchdog_stream(workdir: str) -> int:
    """Supervised 4-stream throughput round with one hung stream: the
    watchdog catches it, the supervisor restarts it once, the round
    completes degraded — never wedged."""
    from nds_tpu.nds import streams
    from nds_tpu.nds.throughput import run_streams
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.resilience import faults
    from nds_tpu.resilience.watchdog import EXIT_STALLED

    raw = os.path.join(workdir, "raw")
    sdir = os.path.join(workdir, "tstreams")
    out = os.path.join(workdir, "tp")
    streams.generate_query_streams(sdir, 4, templates=[96, 7])
    paths = [os.path.join(sdir, f"query_{i}.sql") for i in range(4)]
    # generous budget: 4 concurrent children on a loaded CI box can see
    # multi-second gaps between legitimate beats (a single big-table
    # parse is one C call — no beat can land mid-parse, and on a 1-core
    # box four children serialize it to 4x the isolated time); the
    # injected hang is 120 s, so detection headroom costs nothing
    stall_s = 30.0
    before = obs_metrics.snapshot()
    saved = os.environ.get(faults.FAULTS_ENV)
    # the schedule reaches the CHILDREN via the environment; the scope
    # matches stream query_1's NDS_TPU_STREAM context — and only its
    # first incarnation (the restart renames itself query_1#r1)
    os.environ[faults.FAULTS_ENV] = "stream.query:hang=120@query_1"
    try:
        _elapse, codes = run_streams(
            raw, paths, out, backend="cpu", input_format="raw",
            stall_s=stall_s)
    finally:
        if saved is None:
            os.environ.pop(faults.FAULTS_ENV, None)
        else:
            os.environ[faults.FAULTS_ENV] = saved
        faults.clear()

    if any(codes):
        return _fail(f"supervised round should complete: codes={codes}")
    with open(os.path.join(out, "throughput_summary.json")) as f:
        summary = json.load(f)
    s1 = summary["streams"].get("query_1")
    if not s1:
        return _fail(f"query_1 missing from summary: {summary}")
    if s1["exit_codes"][0] != EXIT_STALLED:
        return _fail(f"child watchdog should have caught the hang "
                     f"(exit {EXIT_STALLED}): {s1['exit_codes']}")
    if s1["restarts"] != 1 or not s1["degraded"]:
        return _fail(f"query_1 should restart ONCE and be marked "
                     f"degraded: {s1}")
    if not s1["stalls"]:
        return _fail(f"stall record missing from summary: {s1}")
    if s1["stalls"][0].get("age_s", 1e9) > 2 * stall_s:
        return _fail(f"stall detected too late (> 2x stall_s): "
                     f"{s1['stalls']}")
    for name, s in summary["streams"].items():
        if name != "query_1" and s["restarts"]:
            return _fail(f"healthy stream {name} restarted: {s}")
        if s["completed"] != 2:
            return _fail(f"{name} should complete 2 queries: {s}")
    # the hung child's watchdog dumped an all-thread stall report
    # (streams permute query order, so find it by content: only the
    # in-process watchdog can capture thread stacks)
    reports = [f for f in os.listdir(out) if f.startswith("stall-")]
    child_dump = None
    for f in reports:
        with open(os.path.join(out, f)) as fh:
            doc = json.load(fh)
        if "threads" in doc:
            child_dump = doc
            break
    if child_dump is None:
        return _fail(f"no child stall report with thread stacks "
                     f"in {reports}")
    for key in ("unit", "query", "phase", "age_s", "stall_s",
                "threads", "metrics"):
        if key not in child_dump:
            return _fail(f"stall report missing {key!r}: "
                         f"{sorted(child_dump)}")
    if (child_dump["unit"] != "query_1"
            or not child_dump["threads"]):
        return _fail(f"stall report should blame stream query_1 with "
                     f"non-empty stacks: unit={child_dump['unit']}")
    delta = obs_metrics.delta(before, obs_metrics.snapshot())
    counters = delta.get("counters", {})
    if counters.get("stream_restarts_total") != 1:
        return _fail(f"stream_restarts_total delta: {counters}")
    print("OK: watchdog stream (hang caught by child watchdog, "
          "killed, restarted once, round completed degraded)")
    return 0


def run_corrupt_load(workdir: str) -> int:
    """Byte-flip one raw chunk under digest verification: the load
    fails fast with CorruptArtifact, zero retries, reported."""
    from nds_tpu.io import integrity
    from nds_tpu.nds.power import SUITE
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.resilience import faults
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig

    raw = os.path.join(workdir, "raw")
    sdir = os.path.join(workdir, "streams")
    jsons = os.path.join(workdir, "json_corrupt")
    tlog = os.path.join(workdir, "time_corrupt.csv")
    table = "catalog_page"
    integrity.write_manifest(os.path.join(raw, table))
    integrity.set_verify(True)
    cfg = EngineConfig(overrides={"engine.backend": "cpu"})
    before = obs_metrics.snapshot()
    faults.install(f"io.read:corrupt@{table}", seed=7)
    err = None
    try:
        power_core.run_query_stream(
            SUITE, raw, os.path.join(sdir, "query_0.sql"), tlog,
            config=cfg, input_format="raw",
            json_summary_folder=jsons)
    except integrity.CorruptArtifact as exc:
        err = exc
    finally:
        faults.clear()
        integrity.set_verify(None)
    if err is None:
        return _fail("corrupt chunk should fail the load with "
                     "CorruptArtifact")
    msg = str(err)
    if table not in msg or "sha256 expected" not in msg:
        return _fail(f"CorruptArtifact should name the file and "
                     f"digests: {msg}")
    delta = obs_metrics.delta(before, obs_metrics.snapshot())
    counters = delta.get("counters", {})
    if counters.get("query_retries_total"):
        return _fail(f"corruption must NEVER be retried: {counters}")
    if counters.get("corrupt_artifacts_total") != 1:
        return _fail(f"corrupt_artifacts_total delta: {counters}")
    loads = [f for f in os.listdir(jsons) if "load_warehouse" in f]
    if not loads:
        return _fail(f"no load_warehouse BenchReport in {jsons}")
    with open(os.path.join(jsons, loads[0])) as f:
        rep = json.load(f)
    if rep["queryStatus"] != ["Failed"] or rep.get("retries") != 0:
        return _fail(f"load report should be Failed with retries=0: "
                     f"{rep['queryStatus']} retries={rep.get('retries')}")
    if not any("corrupt artifact" in e for e in rep["exceptions"]):
        return _fail(f"load report lost the corruption text: "
                     f"{rep['exceptions']}")
    print("OK: corrupt chunk (load failed fast with CorruptArtifact, "
          "0 retries, reported)")
    return 0


def main() -> int:
    # pin the reloadable-codegen flag BEFORE any scenario initializes
    # jax: the cache-corruption scenario's warm rerun asserts zero
    # compiles, which needs persisted CPU executables to deserialize
    from nds_tpu import cache as plan_cache
    plan_cache.ensure_reloadable_codegen()
    with tempfile.TemporaryDirectory(prefix="nds_chaos_") as workdir:
        rc = run_chaos_stream(workdir)
        rc |= run_journal_check(workdir)
        rc |= run_ladder_stream(workdir)
        rc |= run_consensus_demotion(workdir)
        rc |= run_cache_corruption(workdir)
        rc |= run_watchdog_stream(workdir)
        # LAST: really mutates the shared raw data
        rc |= run_corrupt_load(workdir)
    return rc


if __name__ == "__main__":
    sys.exit(main())
