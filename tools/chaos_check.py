"""Deterministic chaos gate: a tiny NDS power stream under injected
faults, asserted end-to-end.

tier-1 (via tools/static_checks.py) runs a 3-query NDS power stream on
the CPU backend with a FIXED fault schedule — one transient
device.execute OOM (must be retried and succeed, ``retries=1``,
status ``Completed``) and one deterministic plan fault (must fail
FAST: one attempt, ``gave_up_reason=deterministic``) — then checks the
per-query JSON summaries, the TimeLog CSV (the stream never aborts),
the resilience metrics counters, and the PhaseJournal resume
round-trip. The schedule is seeded, so every CI run replays the exact
same failure sequence; a regression in classification, retry
accounting, or journaling fails here before any differential tier
spins up a device.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCALE = 0.01
TEMPLATES = [96, 7, 93]
# query7 dies once with an injected device OOM (transient: retried);
# query93 dies at plan time (deterministic: never retried)
SCHEDULE = "device.execute:oom@query7,plan:deterministic@query93"


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def run_chaos_stream(workdir: str) -> int:
    from nds_tpu.nds import gen_data, streams
    from nds_tpu.nds.power import SUITE
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.resilience import faults
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    from nds_tpu.utils.timelog import TimeLog

    raw = os.path.join(workdir, "raw")
    sdir = os.path.join(workdir, "streams")
    jsons = os.path.join(workdir, "json")
    tlog = os.path.join(workdir, "time.csv")
    gen_data.generate_data_local(SCALE, 2, raw, workers=2)
    streams.generate_query_streams(sdir, 1, templates=TEMPLATES)

    cfg = EngineConfig(overrides={
        "engine.backend": "cpu",
        "engine.retry.base_delay_s": "0.01",
        "engine.retry.max_attempts": "3",
    })
    before = obs_metrics.snapshot()
    plan = faults.install(SCHEDULE, seed=7)
    try:
        failures = power_core.run_query_stream(
            SUITE, raw, os.path.join(sdir, "query_0.sql"), tlog,
            config=cfg, input_format="raw",
            json_summary_folder=jsons)
    finally:
        faults.clear()
    delta = obs_metrics.delta(before, obs_metrics.snapshot())
    counters = delta.get("counters", {})

    if failures != 1:
        return _fail(f"expected exactly the deterministic failure, "
                     f"got {failures}")
    summaries = {}
    for f in os.listdir(jsons):
        with open(os.path.join(jsons, f)) as fh:
            s = json.load(fh)
        summaries[s["query"]] = s
    q96, q7, q93 = (summaries.get(f"query{n}") for n in TEMPLATES)
    if not (q96 and q7 and q93):
        return _fail(f"missing summaries: {sorted(summaries)}")
    if q96["queryStatus"] != ["Completed"] or q96.get("retries") != 0:
        return _fail(f"query96 should complete untouched: {q96}")
    if q7["queryStatus"] != ["Completed"] or q7.get("retries") != 1:
        return _fail(f"query7 should complete after ONE retry: "
                     f"status={q7['queryStatus']} "
                     f"retries={q7.get('retries')}")
    if (q93["queryStatus"] != ["Failed"]
            or q93.get("gave_up_reason") != "deterministic"
            or q93.get("retries") != 0):
        return _fail(f"query93 should fail fast without retry: {q93}")
    if "injected deterministic fault" not in " ".join(q93["exceptions"]):
        return _fail(f"query93 exception text lost: {q93['exceptions']}")
    # the stream never aborts: every query has a TimeLog row
    names = [q for _a, q, _ms in TimeLog.read(tlog)]
    for n in TEMPLATES:
        if f"query{n}" not in names:
            return _fail(f"query{n} missing from TimeLog {names}")
    if counters.get("query_retries_total") != 1:
        return _fail(f"query_retries_total delta: {counters}")
    if counters.get("faults_injected_total") != 2:
        return _fail(f"faults_injected_total delta: {counters}")
    fired = {(sp.site, sp.fired) for sp in plan.specs}
    if fired != {("device.execute", 1), ("plan", 1)}:
        return _fail(f"unexpected firing counts {fired}")
    print("OK: chaos stream (1 transient retried, 1 deterministic "
          "fail-fast, stream completed)")
    return 0


def run_journal_check(workdir: str) -> int:
    from nds_tpu.resilience.journal import (
        JournalMismatch, PhaseJournal, config_digest,
    )
    path = os.path.join(workdir, "bench_state.json")
    digest = config_digest({"scale_factor": 0.01, "backend": "cpu"})
    j = PhaseJournal(path, digest)
    j.reset()
    j.complete("load_test", load_time_s=12.5, rngseed=42)
    j.complete("power_test", power_time_s=3.25)
    # a fresh journal object (the resumed process) replays the state
    j2 = PhaseJournal(path, digest)
    if not j2.load():
        return _fail("journal did not persist")
    if not (j2.done("load_test") and j2.done("power_test")):
        return _fail(f"phases lost: {j2.state}")
    if j2.done("throughput_1"):
        return _fail("phantom phase in journal")
    if j2.timings("load_test") != {"load_time_s": 12.5, "rngseed": 42}:
        return _fail(f"timings drifted: {j2.timings('load_test')}")
    # a different config must refuse to resume (digest guard)
    j3 = PhaseJournal(path, config_digest({"scale_factor": 3000}))
    try:
        j3.load()
    except JournalMismatch:
        pass
    else:
        return _fail("journal accepted a mismatched config digest")
    print("OK: phase journal round-trip + config-digest guard")
    return 0


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="nds_chaos_") as workdir:
        rc = run_chaos_stream(workdir)
        rc |= run_journal_check(workdir)
    return rc


if __name__ == "__main__":
    sys.exit(main())
