"""Serving gate: the query server proven end-to-end on the CPU backend.

tier-1 (via tools/static_checks.py section 10) builds tiny in-memory
NDS + NDS-H warehouses, starts a QueryServer (``engine.backend=tpu`` —
the device executor compiled by CPU XLA, exactly like the chaos ladder
scenarios — with a fresh persistent plan cache), and proves the
acceptance contract:

1. **warmup** — one request per (suite, template) pays every compile;
2. **mixed load** — literal-VARIANT requests across 6 templates, 3
   tenants, 8 concurrent in flight: every request completes, with
   ZERO compiles and ZERO plan-cache misses after warmup
   (``compiles_total`` / ``compile_cache_misses_total`` deltas), and
   the plan-cache entry count UNCHANGED from warmup — same-template
   literal variants share one entry (parameterized fingerprints,
   sql/params.py);
3. **oracle** — every load response's result digest equals a
   sequential power-run-style replay of the same statements on a
   fresh session (identical engine, identical programs);
4. **observability** — the OpenMetrics exposition validates and
   carries tenant-labeled request counters + latency quantiles;
   every per-request summary passes the BenchReport schema
   (check_trace_schema --summary semantics) and ``ndsreport analyze``
   derives per-tenant p50/p99 from the serve run dir;
5. **brownout** — an oversubscription burst (3x the queue bound, fired
   at once) sheds with ``server_shed_total`` > 0, every ADMITTED
   request still completes correctly, and the server keeps answering
   afterward (shed, never collapse);
6. **wire** — the asyncio TCP JSON-lines front answers a short mixed
   load (tools/ndsload.py --port against a live socket);
7. **jitsan verdict** — phases 2-6 run inside an armed jit-sanitizer
   window (nds_tpu/analysis/jitsan.py, live when NDS_TPU_JITSAN=1 as
   static_checks forces): the gate fails on any post-warmup compile
   through the AOT funnel, any undeclared implicit device->host
   transfer, or a window that crossed zero guarded dispatch sites
   (which would mean the guard is unwired, not that serving is clean).
"""

from __future__ import annotations

import asyncio
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import ndsload  # noqa: E402

SCALE = 0.01
NDS_H_TEMPLATES = (1, 5, 6)
NDS_TEMPLATES = (7, 96, 93)
# every base table the three NDS templates (and their literal
# variants) scan
NDS_TABLES = ("store_sales", "store_returns", "date_dim", "store",
              "customer", "customer_address", "customer_demographics",
              "household_demographics", "item", "promotion", "reason",
              "time_dim")


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _build_server(workdir: str):
    from nds_tpu.datagen import tpcds as gen_d
    from nds_tpu.datagen import tpch as gen_h
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds.schema import get_schemas as d_schemas
    from nds_tpu.nds_h.schema import get_schemas as h_schemas
    from nds_tpu.serve import QueryServer
    from nds_tpu.utils.config import EngineConfig

    cfg = EngineConfig(overrides={
        "engine.backend": "tpu",
        "cache.dir": os.path.join(workdir, "plancache"),
        "serve.max_queue": "16",
        "serve.summary_dir": os.path.join(workdir, "serve_json"),
        "engine.retry.base_delay_s": "0.01",
    })
    srv = QueryServer(cfg)
    for t, sch in h_schemas().items():
        srv.register_table(
            from_arrays(t, sch, gen_h.gen_table(t, SCALE)), "nds_h")
    ds = d_schemas()
    for t in NDS_TABLES:
        srv.register_table(
            from_arrays(t, ds[t], gen_d.gen_table(t, SCALE)), "nds")
    return srv, cfg


def _cache_entry_count(cfg) -> int:
    from nds_tpu.cache.store import PlanCache
    return len(PlanCache(cfg.get("cache.dir"), readonly=True).entries())


def _oracle_digests(srv, docs: list) -> dict:
    """Sequential replay on fresh sessions sharing the server's table
    registries and plan cache (readonly consult): qname -> digest."""
    from nds_tpu.engine.scheduler import make_pipeline
    from nds_tpu.engine.session import Session
    from nds_tpu.io.result_io import result_digest
    sessions = {
        "nds": Session.for_nds(
            make_pipeline(srv.config, "tpu"), parameterize=True),
        "nds_h": Session.for_nds_h(
            make_pipeline(srv.config, "tpu"), parameterize=True),
    }
    for suite, s in sessions.items():
        s.tables = srv.sessions[suite].tables
    out = {}
    for doc in docs:
        res = sessions[doc["suite"]].sql(doc["sql"])
        out[doc["qname"]] = result_digest(res)
    return out


def run_serve_gate(workdir: str) -> int:
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.obs.snapshot import to_openmetrics, validate_openmetrics

    srv, cfg = _build_server(workdir)
    srv.start()
    try:
        # -- 1: warmup pays every compile
        warm = ndsload.run_inproc(
            srv, ndsload.warmup_docs(7, NDS_H_TEMPLATES,
                                     NDS_TEMPLATES), 1)
        ws = ndsload.summarize(warm)
        if ws["status"].get("ok") != len(warm):
            return _fail(f"warmup did not complete clean: {ws}")
        entries_warm = _cache_entry_count(cfg)
        if entries_warm < len(NDS_H_TEMPLATES) + len(NDS_TEMPLATES):
            return _fail(f"warmup persisted only {entries_warm} "
                         f"plan-cache entries")

        # everything after warmup runs under an armed jitsan window
        # (analysis/jitsan.py): any post-warmup compile or undeclared
        # implicit device->host transfer is recorded and fails the
        # gate below — the runtime twin of the counter deltas phase 2
        # already asserts. No-op (arm() returns False) unless
        # NDS_TPU_JITSAN=1, so the standalone tool stays unchanged.
        from nds_tpu.analysis import jitsan
        jitsan_armed = jitsan.arm("serve_check.post_warmup")

        # -- 2: mixed literal-variant load, zero compiles/misses, no
        #       new cache entries (variants share one fingerprint)
        before = obs_metrics.snapshot()
        docs = ndsload.build_requests(24, 7, tenants=3,
                                      nds_h_templates=NDS_H_TEMPLATES,
                                      nds_templates=NDS_TEMPLATES)
        resp = ndsload.run_inproc(srv, docs, 8)
        ls = ndsload.summarize(resp)
        if ls["status"].get("ok") != len(docs):
            return _fail(f"load phase not fully ok: {ls}")
        delta = obs_metrics.delta(
            before, obs_metrics.snapshot()).get("counters", {})
        if delta.get("compiles_total", 0) != 0:
            return _fail(f"warm load compiled "
                         f"{delta['compiles_total']} programs")
        if delta.get("compile_cache_misses_total", 0) != 0:
            return _fail(f"warm load missed the plan cache "
                         f"{delta['compile_cache_misses_total']}x")
        if _cache_entry_count(cfg) != entries_warm:
            return _fail(
                f"literal variants minted new cache entries "
                f"({entries_warm} -> {_cache_entry_count(cfg)})")
        if srv.stats["max_inflight"] < 4:
            return _fail(f"peak in-flight {srv.stats['max_inflight']} "
                         f"< 4 concurrent requests")
        print(f"OK: load {len(docs)} literal-variant requests, "
              f"0 compiles, 0 cache misses, {entries_warm} shared "
              f"entries, p99={ls['latency_ms'].get('p99')}ms, "
              f"max_inflight={srv.stats['max_inflight']}, "
              f"batched={srv.stats['batched']}")

        # -- 3: sequential oracle parity (digest-exact: same engine,
        #       same compiled programs)
        oracle = _oracle_digests(srv, docs)
        for r in resp:
            if r.get("digest") != oracle.get(r.get("qname")):
                return _fail(f"{r.get('qname')}: served digest "
                             f"{r.get('digest')} != oracle "
                             f"{oracle.get(r.get('qname'))}")
        print(f"OK: {len(resp)} responses digest-identical to the "
              f"sequential oracle")

        # -- 4: observability — OpenMetrics + summaries + analyze
        om = to_openmetrics(obs_metrics.snapshot())
        errs = validate_openmetrics(om)
        if errs:
            return _fail(f"OpenMetrics invalid: {errs[:3]}")
        for needle in ('server_requests_total{tenant="tenant0"}',
                       'tenant="tenant0",quantile="0.99"',
                       'tenant="tenant0",quantile="0.50"'):
            if needle not in om:
                return _fail(f"OpenMetrics missing {needle!r}")
        import check_trace_schema
        sdir = cfg.get("serve.summary_dir")
        summaries = [f for f in os.listdir(sdir) if f.endswith(".json")]
        if len(summaries) < len(docs):
            return _fail(f"only {len(summaries)} serve summaries "
                         f"written")
        serrs = []
        for f in summaries:
            serrs.extend(check_trace_schema.validate_summary_file(
                os.path.join(sdir, f)))
        if serrs:
            return _fail(f"serve summary schema errors: {serrs[:3]}")
        from nds_tpu.obs import analyze
        analysis = analyze.analyze_run(sdir)
        tenants = analysis.get("tenants") or {}
        if "tenant0" not in tenants or "p99_ms" not in tenants.get(
                "tenant0", {}):
            return _fail(f"ndsreport analyze derived no per-tenant "
                         f"quantiles: {tenants}")
        print(f"OK: OpenMetrics valid with tenant labels, "
              f"{len(summaries)} schema-clean summaries, analyze "
              f"p99={tenants['tenant0']['p99_ms']}ms for tenant0")

        # -- 5: brownout — 3x queue-bound burst sheds, never collapses
        bdocs = ndsload.build_requests(48, 8, tenants=3,
                                       nds_h_templates=NDS_H_TEMPLATES,
                                       nds_templates=NDS_TEMPLATES)
        burst = ndsload.burst_inproc(srv, bdocs)
        bs = ndsload.summarize(burst)
        shed = bs["status"].get("shed", 0)
        bad = bs["status"].get("error", 0)
        if shed == 0:
            return _fail(f"overload burst shed nothing: {bs}")
        if bad:
            return _fail(f"burst produced {bad} errors (shed-not-fail "
                         f"contract): {bs}")
        # every ADMITTED burst request completed with oracle rows
        admitted = [r for r in burst if r.get("status") == "ok"]
        byname = {d["qname"]: d for d in bdocs}
        boracle = _oracle_digests(
            srv, [byname[r["qname"]] for r in admitted])
        for r in admitted:
            if r.get("digest") != boracle.get(r.get("qname")):
                return _fail(f"burst {r.get('qname')}: served digest "
                             f"!= oracle under overload")
        if obs_metrics.snapshot()["counters"].get(
                "server_shed_total", 0) <= 0:
            return _fail("server_shed_total did not move")
        # the server still answers after the burst
        post = ndsload.run_inproc(
            srv, ndsload.build_requests(4, 9, tenants=1,
                                        nds_h_templates=NDS_H_TEMPLATES,
                                        nds_templates=NDS_TEMPLATES), 2)
        ps = ndsload.summarize(post)
        if ps["status"].get("ok") != 4:
            return _fail(f"server unhealthy after burst: {ps}")
        print(f"OK: burst shed {shed}/{len(burst)} with "
              f"{bs['status'].get('ok', 0)} admitted completions; "
              f"server healthy after")

        # -- 6: the TCP JSON-lines front serves a short mixed load
        async def _tcp_phase():
            from nds_tpu.serve.net import request_many, start_tcp
            tcp = await start_tcp(srv, "127.0.0.1", 0)
            port = tcp.sockets[0].getsockname()[1]
            tdocs = ndsload.build_requests(
                8, 11, tenants=2, nds_h_templates=NDS_H_TEMPLATES,
                nds_templates=NDS_TEMPLATES)
            out = await request_many("127.0.0.1", port, tdocs, 4)
            tcp.close()
            await tcp.wait_closed()
            return out

        tcp_resp = asyncio.run(_tcp_phase())
        ts = ndsload.summarize(tcp_resp)
        if ts["status"].get("ok") != len(tcp_resp):
            return _fail(f"TCP front failed requests: {ts}")
        print(f"OK: TCP front answered {len(tcp_resp)}/"
              f"{len(tcp_resp)} requests")

        # -- 7: jitsan verdict over phases 2-6
        if jitsan_armed:
            v = jitsan.disarm()
            if v["compiles"]:
                return _fail(f"jitsan: {len(v['compiles'])} "
                             f"post-warmup compile(s): "
                             f"{[c['kind'] for c in v['compiles']]}")
            if v["undeclared_transfers"]:
                return _fail(
                    f"jitsan: {len(v['undeclared_transfers'])} "
                    f"undeclared implicit transfer(s): "
                    f"{[t['what'] for t in v['undeclared_transfers']]}")
            if v["dispatches"] == 0:
                return _fail("jitsan: window saw zero dispatch "
                             f"crossings — guard not wired: {v}")
            print(f"OK: jitsan window clean — 0 post-warmup compiles, "
                  f"0 undeclared transfers across {v['dispatches']} "
                  f"guarded dispatches ({v['declared_transfers']} "
                  f"declared read-backs)")
        return 0
    finally:
        # a _fail() mid-gate must not leak an open window into later
        # in-process sections (static_checks runs this in-process);
        # disarm() on an already-closed window is a no-op
        from nds_tpu.analysis import jitsan as _js
        _js.disarm()
        srv.stop()


def main(argv=None) -> int:
    with tempfile.TemporaryDirectory(prefix="nds_serve_check_") as wd:
        rc = run_serve_gate(wd)
    print("SERVE CHECK", "OK" if rc == 0 else "FAILED")
    return rc


if __name__ == "__main__":
    sys.exit(main())
