"""ndsraces: run the concurrency auditor over the tree.

Drives ``nds_tpu/analysis/concurrency.py`` (rule catalog NDSR201-204 +
waiver semantics live there). Configuration comes from
``[tool.ndsraces]`` in pyproject.toml (same shape as ndslint's):

    roots   = ["nds_tpu"]      # directories to audit
    exclude = []               # path substrings to skip
    rules   = []               # rule-id allowlist ([] = all)

Waivers are per-line and must carry a justification:

    self.dumps + 1  # ndsraces: waive[NDSR201] -- signal-path fallback

Exit 0 when the tree is clean (waived findings print with their notes
under -v); exit 1 on any unwaived violation, malformed waiver, or stale
waiver. ``--waiver-report`` prints the tree-wide waiver-hygiene report
(shared with ``ndslint --waiver-report``: per-rule counts for BOTH
tools, stale waivers flagged); ``--locksan-selftest`` seeds a
deliberate lock-order inversion through the runtime sanitizer
(nds_tpu/analysis/locksan.py) and exits 0 only when it is caught — the
tier-1 proof the detector fires. Run by tools/static_checks.py.
"""

from __future__ import annotations

import argparse
import os
import pathlib
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import ndslint  # noqa: E402

from nds_tpu.analysis import concurrency, lint_rules  # noqa: E402

DEFAULT_CONFIG = {
    "roots": ["nds_tpu"],
    "exclude": [],
    "rules": [],
}


def load_config(repo: pathlib.Path) -> dict:
    """[tool.ndsraces] from pyproject.toml, through ndslint's parser
    (one config grammar for both gates)."""
    cfg = dict(DEFAULT_CONFIG)
    cfg.update(ndslint.load_section(repo, "tool.ndsraces"))
    return cfg


def run(repo: pathlib.Path, verbose: bool = False,
        cfg: "dict | None" = None) -> int:
    cfg = load_config(repo) if cfg is None else cfg
    sources = ndslint.collect_sources(repo, cfg)
    enabled = set(cfg["rules"]) or None
    res = concurrency.audit_sources(sources, enabled=enabled)
    for v in res.violations + res.errors:
        print(v)
    if verbose:
        for v in res.waived:
            print(f"{v.path}:{v.line}: {v.rule} waived -- "
                  f"{v.waiver_note}")
    bad = len(res.violations) + len(res.errors)
    print(f"{'FAIL' if bad else 'OK'}: {bad} violation(s), "
          f"{len(res.waived)} waived, {len(sources)} file(s)")
    return 1 if bad else 0


def waiver_report(repo: pathlib.Path, verbose: bool = False) -> int:
    """The shared ndslint+ndsraces waiver-hygiene report."""
    lint_cfg = ndslint.load_config(repo)
    races_cfg = load_config(repo)
    results = {
        "ndslint": lint_rules.lint_sources(
            ndslint.collect_sources(repo, lint_cfg),
            enabled=set(lint_cfg["rules"]) or None),
        "ndsraces": concurrency.audit_sources(
            ndslint.collect_sources(repo, races_cfg),
            enabled=set(races_cfg["rules"]) or None),
    }
    for line in lint_rules.waiver_report(results, verbose=verbose):
        print(line)
    stale = sum(1 for res in results.values() for e in res.errors
                if "matches no violation" in e.msg)
    print(f"{'FAIL' if stale else 'OK'}: {stale} stale waiver(s)")
    return 1 if stale else 0


def locksan_selftest() -> int:
    from nds_tpu.analysis import locksan
    ok = locksan.selftest()
    print(f"{'OK' if ok else 'FAIL'}: locksan "
          f"{'caught' if ok else 'MISSED'} the seeded lock-order "
          f"inversion + re-entrant acquire")
    return 0 if ok else 1


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print waived findings with their notes")
    ap.add_argument("--waiver-report", action="store_true",
                    help="print the shared ndslint+ndsraces waiver "
                         "hygiene report instead of auditing")
    ap.add_argument("--locksan-selftest", action="store_true",
                    help="seed a lock-order inversion through the "
                         "runtime sanitizer; exit 0 iff it is caught")
    args = ap.parse_args(argv)
    repo = pathlib.Path(__file__).resolve().parent.parent
    if args.locksan_selftest:
        return locksan_selftest()
    if args.waiver_report:
        return waiver_report(repo, verbose=args.verbose)
    return run(repo, verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
