"""Pipelined-execution gate: prefetch on == serial results, stalls seen.

tier-1 (via tools/static_checks.py) proves the double-buffered
host<->device pipeline (nds_tpu/engine/pipeline_io.py; README
"Pipelined execution") end-to-end on the CPU backend:

1. **chunked parity + overlap** — a 3-query NDS-H power stream
   (q1/q3/q6) runs FORCED onto the chunked placement with a chunk size
   small enough for 8+ chunks per streamed table, twice:
   ``engine.prefetch.enabled=off`` (the serial loops) then
   ``engine.prefetch.depth=2``. The gate asserts every query Completed
   in both runs, result rows are byte-identical, the two runs compiled
   EXACTLY the same number of programs (prefetch must not perturb the
   chunkscan fingerprints), at least one prefetch-run summary measured
   ``prefetch_hidden_s > 0`` (host staging actually overlapped
   compute), and the prefetch run's wall-clock is no worse than serial
   (a noise-tolerant bound on shared CI hardware; the >=1.2x win is
   ``--full``'s assertion).
2. **occupancy attribution** — ``ndsreport``-level invariants over the
   prefetch run: categories+residual == wall-clock per query with the
   new ``prefetch_wait`` category in place, occupancy present on
   pipeline-evidence rows, and the serial-vs-prefetch diff passes (no
   phantom PIPELINE-STALLED between them).
3. **boundary pipelining** — the same stream with
   ``engine.prefetch.boundary=on``: query N+1 dispatches while query
   N's result is still in flight. Rows stay byte-identical, every
   summary is schema-valid, and the journal holds all three
   completions (drain/resume bookkeeping survives the overlap).

``--full`` additionally runs a larger warehouse and asserts the
ROADMAP acceptance shape: prefetch depth 2 beats the serial phase-A
wall-clock by >=1.2x at 8+ chunks.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCALE = 0.01
TEMPLATES = (1, 3, 6)
CHUNK_ROWS = 4096
STREAM_BYTES = 50_000
# smoke tolerance: "no worse than serial" on shared CI hardware means
# within this factor (thread setup + scheduling jitter on 3 tiny
# queries); the real >=1.2x win is asserted under --full
SMOKE_SLACK = 1.25


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _write_stream(path: str) -> None:
    from nds_tpu.nds_h import streams as hstreams
    parts = [f"-- Template file: {qn}\n\n"
             f"{hstreams.render_query(qn, None, stream=0)}\n"
             for qn in TEMPLATES]
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write("\n".join(parts))


def _summaries(jsons: str) -> dict:
    from nds_tpu.obs import analyze
    out = {}
    for name in os.listdir(jsons):
        if not analyze.is_report_basename(name):
            continue
        with open(os.path.join(jsons, name)) as f:
            s = json.load(f)
        if isinstance(s, dict) and "query" in s and "queryStatus" in s:
            out[s["query"]] = s
    return out


def _run_stream(workdir: str, raw: str, stream: str, label: str,
                overrides: dict) -> "dict | None":
    from nds_tpu.nds_h.power import SUITE
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    jsons = os.path.join(workdir, f"json_{label}")
    out = os.path.join(workdir, f"rows_{label}")
    cfg = EngineConfig(overrides={
        "engine.backend": "tpu",            # chunked universe on the
        "engine.placement.force": "chunked",  # local CPU jax backend
        "engine.stream_bytes": STREAM_BYTES,
        "engine.chunk_rows": CHUNK_ROWS,
        **overrides,
    })
    failures = power_core.run_query_stream(
        SUITE, raw, stream, os.path.join(workdir, f"{label}.csv"),
        config=cfg, input_format="raw", json_summary_folder=jsons,
        output_prefix=out)
    if failures:
        print(f"FAIL: {failures} query failure(s) in the {label} run")
        return None
    return {"summaries": _summaries(jsons), "rows": out,
            "jsons": jsons}


def _compiles(summaries: dict) -> int:
    total = 0
    for s in summaries.values():
        c = (s.get("metrics") or {}).get("counters", {})
        total += int(c.get("compiles_total", 0)
                     + c.get("recompiles_total", 0))
    return total


def _walls(summaries: dict) -> float:
    return sum(float(s["queryTimes"][-1]) for s in summaries.values())


def _rows_identical(a: dict, b: dict) -> "str | None":
    from nds_tpu.io.result_io import read_result
    for qn in TEMPLATES:
        q = f"query{qn}"
        ra = read_result(os.path.join(a["rows"], q))
        rb = read_result(os.path.join(b["rows"], q))
        if ra is None or rb is None:
            return f"{q} result rows missing on disk"
        if not ra.equals(rb):
            return f"{q} rows differ"
        sa = a["summaries"].get(q, {}).get("result_digest")
        sb = b["summaries"].get(q, {}).get("result_digest")
        if sa != sb:
            return f"{q} result digests differ ({sa} != {sb})"
    return None


def run_parity(workdir: str) -> "tuple[int, dict | None, dict | None]":
    from nds_tpu.nds_h import gen_data
    raw = os.path.join(workdir, "raw")
    stream = os.path.join(workdir, "streams", "stream.sql")
    gen_data.generate_data_local(SCALE, 2, raw, workers=2)
    _write_stream(stream)
    serial = _run_stream(workdir, raw, stream, "serial",
                         {"engine.prefetch.enabled": "off"})
    if serial is None:
        return 1, None, None
    pre = _run_stream(workdir, raw, stream, "prefetch",
                      {"engine.prefetch.depth": "2"})
    if pre is None:
        return 1, None, None
    bad = _rows_identical(serial, pre)
    if bad:
        return _fail(bad), None, None
    cs, cp = _compiles(serial["summaries"]), _compiles(pre["summaries"])
    if cs != cp:
        return _fail(f"prefetch perturbed compile counts "
                     f"({cs} serial vs {cp} prefetch) — the chunkscan "
                     f"fingerprint must not see the pipeline"), None, \
            None
    hidden = [
        (q, (s.get("engineTimings") or {}).get("prefetch_hidden_s"))
        for q, s in pre["summaries"].items()]
    if not any(h and h > 0 for _q, h in hidden):
        return _fail(f"no query measured prefetch_hidden_s > 0 "
                     f"({hidden}) — nothing overlapped"), None, None
    ws, wp = _walls(serial["summaries"]), _walls(pre["summaries"])
    if wp > ws * SMOKE_SLACK:
        return _fail(f"prefetch run slower than serial past the noise "
                     f"bound: {wp:.0f} ms vs {ws:.0f} ms"), None, None
    print(f"OK: parity — rows identical, compiles {cs}=={cp}, "
          f"hidden overlap measured, walls {ws:.0f} -> {wp:.0f} ms "
          f"({ws / max(wp, 1e-9):.2f}x)")
    return 0, serial, pre


def run_attribution(serial: dict, pre: dict) -> int:
    from nds_tpu.obs import analyze
    a = analyze.analyze_run(serial["jsons"], with_trace=False)
    b = analyze.analyze_run(pre["jsons"], with_trace=False)
    for run, tag in ((a, "serial"), (b, "prefetch")):
        for row in run["queries"]:
            total = (sum(row["categories"].values())
                     + row["residual_ms"])
            if abs(total - row["wall_ms"]) > 1e-6:
                return _fail(
                    f"{tag} {row['query']}: categories+residual "
                    f"{total:.3f} != wall {row['wall_ms']:.3f}")
    if not any("occupancy" in r for r in b["queries"]):
        return _fail("prefetch run rows carry no occupancy column")
    d = analyze.diff_runs(a, b)
    stalled = [e for e in d.get("pipeline_changes", [])
               if e.get("stalled")]
    if stalled:
        return _fail(f"serial->prefetch diff flagged PIPELINE-STALLED "
                     f"{stalled} — the overlap made stalls WORSE?")
    if not d["passed"]:
        # compile-count flags etc. are fine; hard failures are not
        return _fail("serial-vs-prefetch diff failed the gate")
    print("OK: attribution — invariant holds with prefetch_wait, "
          "occupancy present, diff clean")
    return 0


def run_boundary(workdir: str, serial: dict) -> int:
    from tools.check_trace_schema import validate_summary
    raw = os.path.join(workdir, "raw")
    stream = os.path.join(workdir, "streams", "stream.sql")
    bnd = _run_stream(workdir, raw, stream, "boundary",
                      {"engine.prefetch.depth": "2",
                       "engine.prefetch.boundary": "on"})
    if bnd is None:
        return 1
    bad = _rows_identical(serial, bnd)
    if bad:
        return _fail(f"boundary run: {bad}")
    for q, s in bnd["summaries"].items():
        errs = validate_summary(s)
        if errs:
            return _fail(f"boundary {q} summary schema: {errs}")
    # journal: every statement completed exactly once despite the
    # overlapped brackets (the drain/resume contract's bookkeeping)
    jpath = os.path.join(bnd["jsons"], "power-nds_h_queries.json")
    if not os.path.exists(jpath):
        return _fail(f"boundary journal missing at {jpath}")
    with open(jpath) as f:
        journal = json.load(f)
    done = {name for name, e in (journal.get("queries") or {}).items()
            if e.get("done")}
    want = {f"query{qn}" for qn in TEMPLATES}
    if not want <= done:
        return _fail(f"boundary journal incomplete: {sorted(done)}")
    print("OK: boundary pipelining — rows identical, summaries "
          "schema-valid, journal complete")
    return 0


def run_full(workdir: str) -> int:
    """The acceptance shape (ISSUE 15 / ROADMAP item 2): >=1.2x
    phase-A wall-clock improvement over serial at 8+ chunks. Run on
    real hardware (or an unloaded host) — CI smoke only asserts
    no-worse."""
    from nds_tpu.nds_h import gen_data
    raw = os.path.join(workdir, "raw_full")
    stream = os.path.join(workdir, "streams", "stream.sql")
    gen_data.generate_data_local(0.05, 2, raw, workers=2)
    _write_stream(stream)
    serial = _run_stream(workdir, raw, stream, "serial_full",
                         {"engine.prefetch.enabled": "off"})
    if serial is None:
        return 1
    pre = _run_stream(workdir, raw, stream, "prefetch_full",
                      {"engine.prefetch.depth": "2"})
    if pre is None:
        return 1
    bad = _rows_identical(serial, pre)
    if bad:
        return _fail(bad)
    ws, wp = _walls(serial["summaries"]), _walls(pre["summaries"])
    ratio = ws / max(wp, 1e-9)
    if ratio < 1.2:
        return _fail(f"prefetch improvement {ratio:.2f}x < 1.2x "
                     f"({ws:.0f} -> {wp:.0f} ms)")
    print(f"OK: full — {ratio:.2f}x wall-clock improvement "
          f"({ws:.0f} -> {wp:.0f} ms)")
    return 0


def main(argv=None) -> int:
    full = "--full" in (sys.argv[1:] if argv is None else argv)
    with tempfile.TemporaryDirectory(prefix="nds_pipeline_") as wd:
        print("-- pipeline_check: parity --")
        rc, serial, pre = run_parity(wd)
        if rc:
            return rc
        print("-- pipeline_check: attribution --")
        rc = run_attribution(serial, pre)
        if rc:
            return rc
        print("-- pipeline_check: boundary --")
        rc = run_boundary(wd, serial)
        if rc:
            return rc
        if full:
            print("-- pipeline_check: full (>=1.2x) --")
            rc = run_full(wd)
            if rc:
                return rc
    print("PIPELINE CHECK OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
