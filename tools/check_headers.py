"""CI quality gate: every Python source file must open with a module
docstring that cites its design intent (this repo's documentation
contract — the analog of the reference's license-header gate,
`/.github/workflows/license-header-check.yml`).

Exit code 0 when clean; prints each offending file otherwise.
"""

from __future__ import annotations

import ast
import pathlib
import sys

ROOTS = ("nds_tpu", "tests", "tools")
EXEMPT = {"__init__.py"}


def main() -> int:
    repo = pathlib.Path(__file__).resolve().parent.parent
    bad = []
    for root in ROOTS:
        for p in sorted((repo / root).rglob("*.py")):
            if p.name in EXEMPT:
                continue
            try:
                tree = ast.parse(p.read_text())
            except SyntaxError as exc:
                bad.append(f"{p}: syntax error: {exc}")
                continue
            if ast.get_docstring(tree) is None:
                bad.append(f"{p}: missing module docstring")
    for line in bad:
        print(line)
    print(f"{'FAIL' if bad else 'OK'}: "
          f"{len(bad)} file(s) missing headers")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
