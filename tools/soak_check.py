"""Chaos soak gate: interrupt a real benchmark run, resume it, prove
nothing was lost and nothing ran twice.

The resilience stack now claims a strong invariant: an interrupted run
loses AT MOST the one in-flight query (README "Preemption & resume").
This gate makes that claim mechanically testable against real power-run
subprocesses on a tiny in-memory warehouse (SF0.01):

- **smoke** (default; tier-1 section 9 via tools/static_checks.py) —
  two interruption scenarios against a 3-query NDS power stream:

  1. *SIGTERM drain*: the victim query is wedged by an injected
     ``stream.query:hang``; SIGTERM arrives mid-query, the drain
     deadline (``NDS_TPU_DRAIN_S``) expires, the process journals the
     in-flight query as explicitly not-done and exits 75 (resumable).
  2. *kill -9 mid-query*: no drain, no handler, no flush — the hard
     case. The journal's pre-dispatch start mark is the only evidence.

  After each interruption the run resumes with ``--resume`` and the
  gate asserts: the resumed run completes every statement, the final
  per-query result digests are byte-identical to an uninterrupted
  clean run's, every statement completed exactly ONCE (journal start/
  done accounting — the killed query restarted, nothing else did), the
  merged phase report (``merged-*.json``) bills each query once, and
  ``ndsreport``-side analysis sees no double-billed rows. The
  stale-state path never fires: ``journal_resets_total`` stays zero
  and the final metric row (Power Test Time) is regenerated from THIS
  run's journal, never replayed from a stale artifact.

- **--full N** — N additional seeded randomized rounds (kind x victim
  drawn from a seeded RNG: SIGTERM drains and hard kills), plus an
  injected-OOM round (a transient device OOM recovered by the retry
  machinery composes with a mid-run kill: the resume replays the
  recovered completion instead of re-paying it), a torn-journal round
  (the journal is byte-flipped between incarnations: the resume must
  degrade to a warned fresh start, count ``journal_resets_total``,
  surface it in the summaries' ``degradations`` block, and STILL
  converge to the clean digests), a kill-during-maintenance round (a
  randomized LF_* refresh function is hard-killed inside ``dml.apply``
  after its commit-journal START-mark; ``--resume`` must apply every
  refresh function exactly once and a second resume must be a no-op —
  the WRITE path honors the same at-most-once contract as the read
  path, tools/maint_check.py proves the result-level half) and an
  NDS-H drain round — both suites survive, not just NDS.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCALE = 0.01
TEMPLATES = [96, 7, 93]
DRAIN_S = "2"          # short deadline: the gate must not idle 30 s
HANG_S = 90            # far past every timeout the gate uses
WAIT_S = 240           # per-subprocess safety timeout


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


# ------------------------------------------------------------ plumbing

def _power_cmd(suite: str, raw: str, stream: str, out_dir: str,
               resume: bool = False, subset=None) -> list:
    mod = "nds_tpu.nds.power" if suite == "nds" else "nds_tpu.nds_h.power"
    cmd = [sys.executable, "-m", mod, raw, stream,
           os.path.join(out_dir, "time.csv"), "--backend", "cpu",
           "--input_format", "raw", "--json_summary_folder", out_dir]
    if subset:
        cmd += ["--query_subset", *subset]
    if resume:
        cmd.append("--resume")
    return cmd


def _env(faults: str | None = None) -> dict:
    from nds_tpu.utils.power_core import subprocess_env
    env = subprocess_env("cpu")
    env["NDS_TPU_DRAIN_S"] = DRAIN_S
    env.pop("NDS_TPU_FAULTS", None)
    if faults:
        env["NDS_TPU_FAULTS"] = faults
    return env


def _journal_path(suite: str, out_dir: str) -> str:
    return os.path.join(out_dir, f"power-{suite}_queries.json")


def _read_journal(suite: str, out_dir: str) -> dict | None:
    try:
        with open(_journal_path(suite, out_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _wait_for_start(suite: str, out_dir: str, qname: str,
                    timeout_s: float = 120.0) -> bool:
    """Poll the (atomic) query journal until ``qname`` has a start
    mark and no completion — the deterministic "the child is inside
    the hung victim query" signal the interruption scenarios key on
    (the start is journaled immediately before dispatch, and the
    injected hang wedges the dispatch)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        doc = _read_journal(suite, out_dir)
        q = (doc or {}).get("queries", {}).get(qname, {})
        if q.get("starts") and not q.get("done"):
            return True
        time.sleep(0.1)
    return False


def _digests(suite: str, out_dir: str) -> dict:
    doc = _read_journal(suite, out_dir) or {}
    return {q: e.get("result_digest")
            for q, e in doc.get("queries", {}).items() if e.get("done")}


def _summaries(out_dir: str) -> list:
    out = []
    for f in sorted(os.listdir(out_dir)):
        if not f.endswith(".json") or f.startswith("merged-"):
            continue
        try:
            with open(os.path.join(out_dir, f)) as fh:
                s = json.load(fh)
        except ValueError:
            continue
        if isinstance(s, dict) and "query" in s and "queryStatus" in s:
            out.append(s)
    return out


def _interrupt_run(suite: str, raw: str, stream: str, out_dir: str,
                   victim: str, kind: str,
                   subset=None) -> "int | None":
    """Launch a power run with ``victim`` wedged by an injected hang,
    wait (via the journal) until the child is inside it, interrupt
    (``kind``: "term" = SIGTERM drain, "kill" = SIGKILL), and return
    the exit code (None = scenario plumbing failed)."""
    os.makedirs(out_dir, exist_ok=True)
    proc = subprocess.Popen(
        _power_cmd(suite, raw, stream, out_dir, subset=subset),
        env=_env(f"stream.query:hang={HANG_S}@{victim}"))
    try:
        if not _wait_for_start(suite, out_dir, victim):
            proc.kill()
            proc.wait()
            print(f"FAIL: {victim} never journaled a start before the "
                  f"interrupt window")
            return None
        # the start mark lands immediately before the dispatch the
        # hang wedges; a short beat puts the child deterministically
        # INSIDE the victim, then interrupt
        time.sleep(0.5)
        proc.send_signal(signal.SIGTERM if kind == "term"
                         else signal.SIGKILL)
        return proc.wait(timeout=WAIT_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        print(f"FAIL: interrupted ({kind}) run never exited")
        return None


def _check_converged(suite: str, out_dir: str, clean: dict,
                     victims: "list[str]", scenario: str) -> int:
    """Post-resume invariants: every statement done exactly once, only
    the victims restarted, digests byte-identical to the clean run."""
    doc = _read_journal(suite, out_dir)
    if not doc:
        return _fail(f"{scenario}: no journal after resume")
    queries = doc.get("queries", {})
    for q in clean:
        e = queries.get(q)
        if not e or not e.get("done"):
            return _fail(f"{scenario}: {q} not journaled done after "
                         f"resume: {e}")
        starts = e.get("starts", [])
        want = 2 if q in victims else 1
        if len(starts) != want:
            return _fail(
                f"{scenario}: {q} executed {len(starts)}x (starts="
                f"{starts}), expected {want} — "
                + ("the lost query must re-run exactly once"
                   if q in victims else
                   "a journaled-done query must NEVER re-execute"))
    got = _digests(suite, out_dir)
    if got != clean:
        return _fail(f"{scenario}: result digests diverged from the "
                     f"clean run:\n  clean={clean}\n  got={got}")
    return 0


# ------------------------------------------------------------ scenarios

def run_smoke(workdir: str) -> int:
    from nds_tpu.nds import gen_data, streams
    raw = os.path.join(workdir, "raw")
    sdir = os.path.join(workdir, "streams")
    gen_data.generate_data_local(SCALE, 2, raw, workers=2)
    streams.generate_query_streams(sdir, 1, templates=TEMPLATES)
    stream = os.path.join(sdir, "query_0.sql")
    order = list(streams.parse_query_stream(stream))
    if len(order) < 3:
        return _fail(f"stream too short: {order}")

    # -- clean reference run: the digests every scenario must converge
    # to, and the proof the journal records one done per statement
    clean_dir = os.path.join(workdir, "clean")
    os.makedirs(clean_dir, exist_ok=True)
    rc = subprocess.run(
        _power_cmd("nds", raw, stream, clean_dir), env=_env()
    ).returncode
    if rc != 0:
        return _fail(f"clean run exited {rc}")
    clean = _digests("nds", clean_dir)
    if sorted(clean) != sorted(order) or not all(clean.values()):
        return _fail(f"clean run journaled {clean}, expected digests "
                     f"for {order}")

    # -- scenario 1: SIGTERM drain mid-query -> exit 75 -> --resume
    tdir = os.path.join(workdir, "term")
    victim = order[1]
    rc = _interrupt_run("nds", raw, stream, tdir, victim=victim,
                        kind="term")
    if rc is None:
        return 1
    from nds_tpu.resilience.drain import EXIT_RESUMABLE
    if rc != EXIT_RESUMABLE:
        return _fail(f"drained run should exit {EXIT_RESUMABLE} "
                     f"(resumable), got {rc}")
    doc = _read_journal("nds", tdir) or {}
    ventry = doc.get("queries", {}).get(victim, {})
    if ventry.get("done") or not ventry.get("aborted"):
        return _fail(f"drain deadline should journal {victim} as "
                     f"explicitly not-done: {ventry}")
    if not doc.get("queries", {}).get(order[0], {}).get("done"):
        return _fail(f"{order[0]} lost by the drain: {doc}")
    rc = subprocess.run(
        _power_cmd("nds", raw, stream, tdir, resume=True), env=_env()
    ).returncode
    if rc != 0:
        return _fail(f"resume after drain exited {rc}")
    if _check_converged("nds", tdir, clean, [victim], "sigterm-drain"):
        return 1
    # merged phase report: every statement billed once, all Completed
    mpath = os.path.join(tdir, "merged-power-nds.json")
    if not os.path.exists(mpath):
        return _fail("resumed run left no merged-power-nds.json")
    with open(mpath) as f:
        merged = json.load(f)
    if sorted(merged.get("queries", [])) != sorted(order) \
            or set(merged.get("queryStatus", [])) != {"Completed"} \
            or merged.get("incarnations") != 2:
        return _fail(f"merged phase report wrong: {merged}")
    # analysis-side billing: exactly one row per statement (plus the
    # per-incarnation load_warehouse reports, which are not statements)
    from nds_tpu.obs import analyze
    rows = [r["query"] for r in analyze.analyze_run(
        tdir, with_trace=False)["queries"]
        if r["query"] in set(order)]
    if sorted(rows) != sorted(order):
        return _fail(f"ndsreport would double-bill the merged run: "
                     f"{rows}")
    # the stale-state path never fired, and the metric was regenerated
    for s in _summaries(tdir):
        if s.get("degradations"):
            return _fail(f"no degradation should fire in a clean "
                         f"drain+resume: {s['query']}: "
                         f"{s['degradations']}")
    from nds_tpu.utils.timelog import TimeLog
    rows_t = {q: ms for _a, q, ms in TimeLog.read(
        os.path.join(tdir, "time.csv"))}
    if rows_t.get("Power Test Time", 0) <= 0:
        return _fail(f"resumed run must regenerate the phase metric: "
                     f"{rows_t}")
    print("OK: soak sigterm-drain (exit 75, in-flight query journaled "
          "not-done, resume converged byte-identical, billed once)")

    # -- scenario 2: kill -9 mid-query -> --resume loses only that one
    kdir = os.path.join(workdir, "kill")
    victim = order[2]
    rc = _interrupt_run("nds", raw, stream, kdir, victim=victim,
                        kind="kill")
    if rc is None:
        return 1
    if rc != -signal.SIGKILL:
        return _fail(f"SIGKILL run should die by signal 9, got {rc}")
    doc = _read_journal("nds", kdir) or {}
    if doc.get("queries", {}).get(victim, {}).get("done"):
        return _fail(f"{victim} cannot be journaled done after "
                     f"kill -9 mid-query")
    rc = subprocess.run(
        _power_cmd("nds", raw, stream, kdir, resume=True), env=_env()
    ).returncode
    if rc != 0:
        return _fail(f"resume after kill -9 exited {rc}")
    if _check_converged("nds", kdir, clean, [victim], "kill9"):
        return 1
    print("OK: soak kill-9 (mid-query hard kill lost ONLY the "
          "in-flight query, resume converged byte-identical)")
    return 0


def run_oom_round(workdir: str) -> int:
    """--full round: injected OOM *and* an interruption in one run —
    the retry/ladder machinery and the resume journal must compose.
    query7 eats a transient device OOM (retried to completion), the
    run is then hard-killed inside a hung query93, and the resume must
    converge with the OOM recovery journaled, not repeated."""
    from nds_tpu.nds import streams
    raw = os.path.join(workdir, "raw")
    stream = os.path.join(workdir, "streams", "query_0.sql")
    order = list(streams.parse_query_stream(stream))
    clean = _digests("nds", os.path.join(workdir, "clean"))
    odir = os.path.join(workdir, "oom")
    os.makedirs(odir, exist_ok=True)
    proc = subprocess.Popen(
        _power_cmd("nds", raw, stream, odir),
        env=_env(f"device.execute:oom@query7,"
                 f"stream.query:hang={HANG_S}@{order[-1]}"))
    try:
        if not _wait_for_start("nds", odir, order[-1]):
            proc.kill()
            proc.wait()
            return _fail("oom round: interrupt window never opened")
        time.sleep(0.5)
        proc.kill()
        rc = proc.wait(timeout=WAIT_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        return _fail("oom round: interrupted run never exited")
    if rc != -signal.SIGKILL:
        return _fail(f"oom round: expected SIGKILL death, got {rc}")
    rc = subprocess.run(
        _power_cmd("nds", raw, stream, odir, resume=True),
        env=_env()).returncode
    if rc != 0:
        return _fail(f"oom round: resume exited {rc}")
    if _check_converged("nds", odir, clean, [order[-1]], "oom-round"):
        return 1
    # the OOM recovery happened ONCE, in the first incarnation, and
    # the resume replayed it instead of re-paying the retry
    q7 = (_read_journal("nds", odir) or {}).get("queries", {}).get(
        "query7", {})
    if q7.get("incarnation") != 0 or q7.get("status") != "Completed":
        return _fail(f"oom round: query7's recovered completion should "
                     f"be journaled from incarnation 0: {q7}")
    print("OK: soak oom round (injected OOM retried once, kill -9 "
          "survived, resume replayed the recovery)")
    return 0


def run_torn_journal(workdir: str) -> int:
    """--full round: byte-flip the journal between incarnations. The
    resume must degrade to a warned fresh start (journal_resets_total,
    ``degradations`` in the summaries) and still converge."""
    from nds_tpu.nds import streams
    raw = os.path.join(workdir, "raw")
    stream = os.path.join(workdir, "streams", "query_0.sql")
    order = list(streams.parse_query_stream(stream))
    clean = _digests("nds", os.path.join(workdir, "clean"))
    tdir = os.path.join(workdir, "torn")
    rc = _interrupt_run("nds", raw, stream, tdir, victim=order[1],
                        kind="kill")
    if rc is None:
        return 1
    jpath = _journal_path("nds", tdir)
    with open(jpath, "r+b") as f:
        f.seek(10)
        b = f.read(1)
        f.seek(10)
        f.write(bytes([b[0] ^ 0xFF]))
    rc = subprocess.run(
        _power_cmd("nds", raw, stream, tdir, resume=True), env=_env()
    ).returncode
    if rc != 0:
        return _fail(f"resume over a torn journal exited {rc}")
    got = _digests("nds", tdir)
    if got != clean:
        return _fail(f"torn-journal resume diverged: {got} != {clean}")
    degraded = [s for s in _summaries(tdir)
                if (s.get("degradations") or {}).get("journal_resets")]
    if not degraded:
        return _fail("torn-journal fresh start must surface in the "
                     "summaries' degradations block")
    print("OK: soak torn-journal (resume degraded to a counted, "
          "surfaced fresh start and still converged)")
    return 0


def run_ndsh_drain(workdir: str) -> int:
    """--full round: the NDS-H suite drains + resumes too."""
    from nds_tpu.nds_h import gen_data as h_gen
    from nds_tpu.nds_h import streams as h_streams
    raw = os.path.join(workdir, "raw_h")
    sdir = os.path.join(workdir, "streams_h")
    h_gen.generate_data_local(SCALE, 2, raw)
    h_streams.generate_query_streams(sdir, 1, qualification=False)
    stream = os.path.join(sdir, "stream_0.sql")
    subset = ["query6", "query1", "query12"]
    order = [q for q in h_streams.parse_query_stream(stream)
             if q in subset]
    cdir = os.path.join(workdir, "h_clean")
    os.makedirs(cdir, exist_ok=True)
    rc = subprocess.run(
        _power_cmd("nds_h", raw, stream, cdir, subset=subset),
        env=_env()).returncode
    if rc != 0:
        return _fail(f"NDS-H clean run exited {rc}")
    clean = _digests("nds_h", cdir)
    tdir = os.path.join(workdir, "h_term")
    rc = _interrupt_run("nds_h", raw, stream, tdir, victim=order[1],
                        kind="term", subset=subset)
    if rc is None:
        return 1
    from nds_tpu.resilience.drain import EXIT_RESUMABLE
    if rc != EXIT_RESUMABLE:
        return _fail(f"NDS-H drain should exit {EXIT_RESUMABLE}, "
                     f"got {rc}")
    rc = subprocess.run(
        _power_cmd("nds_h", raw, stream, tdir, resume=True,
                   subset=subset), env=_env()).returncode
    if rc != 0:
        return _fail(f"NDS-H resume exited {rc}")
    if _check_converged("nds_h", tdir, clean, [order[1]],
                        "nds_h-drain"):
        return 1
    print("OK: soak nds_h-drain (both suites drain + resume)")
    return 0


# each LF_* refresh function inserts into exactly one fact table (the
# shipped data_maintenance SQL), and the insert functions run before
# every delete — so a dml.apply hang scoped to the table wedges
# deterministically inside its LF_* function, nowhere else
_LF_TABLE = {"LF_CR": "catalog_returns", "LF_CS": "catalog_sales",
             "LF_I": "inventory", "LF_SR": "store_returns",
             "LF_SS": "store_sales", "LF_WR": "web_returns",
             "LF_WS": "web_sales"}


def run_maintenance_kill(workdir: str, seed: int) -> int:
    """--full round: kill -9 mid-maintenance with a randomized victim
    refresh function wedged inside ``dml.apply`` (after its journal
    START-mark, before its snapshot commit), then ``--resume``. The
    write path's journal accounting must mirror the power loop's: every
    function done exactly once, only the victim restarted, functions
    committed before the kill replayed (never re-applied), and a second
    resume a pure no-op."""
    import random
    from nds_tpu.nds.maintenance import (
        DELETE_FUNCS, INSERT_FUNCS, INVENTORY_DELETE_FUNCS,
        journal_path)
    rng = random.Random(seed)
    victim = rng.choice(sorted(_LF_TABLE))
    table = _LF_TABLE[victim]
    raw = os.path.join(workdir, "raw")
    wh = os.path.join(workdir, "maint_wh")
    refresh = os.path.join(workdir, "maint_refresh")
    mdir = os.path.join(workdir, "maint")
    os.makedirs(mdir, exist_ok=True)
    from nds_tpu.nds import gen_data
    gen_data.generate_refresh_data(SCALE, 1, refresh)
    rc = subprocess.run(
        [sys.executable, "-m", "nds_tpu.nds.transcode", raw, wh,
         os.path.join(mdir, "load_report.txt")], env=_env()).returncode
    if rc != 0:
        return _fail(f"maint round: transcode exited {rc}")

    cmd = [sys.executable, "-m", "nds_tpu.nds.maintenance", wh,
           refresh, os.path.join(mdir, "dm.csv"), "--backend", "cpu",
           "--json_summary_folder", mdir]
    jpath = journal_path(wh, refresh)
    proc = subprocess.Popen(
        cmd, env=_env(f"dml.apply:hang={HANG_S}@{table}"))
    try:
        deadline = time.monotonic() + 120.0
        wedged = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            try:
                with open(jpath) as f:
                    q = json.load(f).get("queries", {}).get(victim, {})
            except (OSError, ValueError):
                q = {}
            if q.get("starts") and not q.get("done"):
                wedged = True
                break
            # ndslint: waive[NDS108] -- deadline-bounded journal poll waiting on an external child process, not a retry; constant interval is the sampling rate
            time.sleep(0.1)
        if not wedged:
            proc.kill()
            proc.wait()
            return _fail(f"maint round: {victim} never journaled a "
                         f"start before the kill window")
        time.sleep(0.5)
        proc.kill()
        rc = proc.wait(timeout=WAIT_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        return _fail("maint round: killed run never exited")
    if rc != -signal.SIGKILL:
        return _fail(f"maint round: expected SIGKILL death, got {rc}")
    with open(jpath) as f:
        before = json.load(f).get("queries", {})
    committed = [q for q, e in before.items() if e.get("done")]
    if before.get(victim, {}).get("done"):
        return _fail(f"maint round: {victim} cannot be done after a "
                     f"mid-dml kill")

    for attempt in ("resume", "idempotent-resume"):
        rc = subprocess.run(cmd + ["--resume"], env=_env(),
                            timeout=WAIT_S).returncode
        if rc != 0:
            return _fail(f"maint round: {attempt} exited {rc}")
        with open(jpath) as f:
            after = json.load(f).get("queries", {})
        funcs = INSERT_FUNCS + DELETE_FUNCS + INVENTORY_DELETE_FUNCS
        for fname in funcs:
            e = after.get(fname, {})
            if not e.get("done"):
                return _fail(f"maint round: {fname} not done after "
                             f"{attempt}: {e}")
            starts = e.get("starts", [])
            want = 2 if fname == victim else 1
            if len(starts) != want:
                return _fail(
                    f"maint round ({attempt}): {fname} dispatched "
                    f"{len(starts)}x (starts={starts}), expected "
                    f"{want} — "
                    + ("the killed function must re-run exactly once"
                       if fname == victim else
                       "a journaled function must NEVER re-apply"))
        for fname in committed:
            if after.get(fname, {}).get("starts") != \
                    before[fname].get("starts"):
                return _fail(f"maint round ({attempt}): {fname} was "
                             f"committed before the kill but "
                             f"re-dispatched after it")
    print(f"OK: soak maintenance round (kill -9 inside {victim}, "
          f"resume applied each refresh function exactly once, second "
          f"resume a no-op)")
    return 0


def run_full(workdir: str, rounds: int, seed: int) -> int:
    import random
    from nds_tpu.nds import streams
    rng = random.Random(seed)
    raw = os.path.join(workdir, "raw")
    stream = os.path.join(workdir, "streams", "query_0.sql")
    order = list(streams.parse_query_stream(stream))
    clean = _digests("nds", os.path.join(workdir, "clean"))
    rc = 0
    for i in range(rounds):
        kind = rng.choice(["term", "kill"])
        vi = rng.randrange(1, len(order))
        victim = order[vi]
        rdir = os.path.join(workdir, f"round{i}")
        code = _interrupt_run("nds", raw, stream, rdir, victim=victim,
                              kind=kind)
        if code is None:
            return 1
        code = subprocess.run(
            _power_cmd("nds", raw, stream, rdir, resume=True),
            env=_env()).returncode
        if code != 0:
            return _fail(f"round {i} ({kind}@{victim}) resume exited "
                         f"{code}")
        rc |= _check_converged("nds", rdir, clean, [victim],
                               f"round{i}:{kind}@{victim}")
        if not rc:
            print(f"OK: soak round {i} ({kind}@{victim}) converged")
    rc |= run_oom_round(workdir)
    rc |= run_torn_journal(workdir)
    rc |= run_maintenance_kill(workdir, seed)
    rc |= run_ndsh_drain(workdir)
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="chaos soak gate: interrupt, resume, prove "
                    "nothing lost and nothing ran twice")
    p.add_argument("--full", type=int, default=0, metavar="N",
                   help="N extra seeded randomized interruption rounds "
                        "plus torn-journal and NDS-H scenarios "
                        "(tier-1 runs only the 2-interruption smoke)")
    p.add_argument("--seed", type=int, default=7)
    args = p.parse_args(argv)
    with tempfile.TemporaryDirectory(prefix="nds_soak_") as workdir:
        rc = run_smoke(workdir)
        if not rc and args.full:
            rc = run_full(workdir, args.full, args.seed)
    return rc


if __name__ == "__main__":
    sys.exit(main())
