"""CI gate for the Chrome trace-event JSONL the engine emits via
``NDS_TPU_TRACE`` (nds_tpu/obs/trace.py): every line must be one JSON
object matching the documented event schema (README "Observability"),
so downstream consumers — Perfetto after array-wrapping, or anything
parsing the JSONL directly — never meet a malformed event.

Schema (one event per line):
  name: non-empty str      ph:  "X" (complete event)
  cat:  str                ts:  number >= 0 (microseconds)
  dur:  number >= 0        pid: int        tid: int
  args: object (optional)

Exit 0 when every line validates; prints each offending line otherwise.
Run by tests/test_observability.py as a tier-1 gate.
"""

from __future__ import annotations

import json
import sys

REQUIRED = {
    "name": str,
    "cat": str,
    "ph": str,
    "ts": (int, float),
    "dur": (int, float),
    "pid": int,
    "tid": int,
}


def validate_event(obj: object) -> list[str]:
    """Schema errors for one parsed event ([] = valid)."""
    errs = []
    if not isinstance(obj, dict):
        return [f"event is {type(obj).__name__}, not an object"]
    for key, typ in REQUIRED.items():
        if key not in obj:
            errs.append(f"missing key {key!r}")
        elif not isinstance(obj[key], typ) or isinstance(obj[key], bool):
            errs.append(f"{key!r} has type {type(obj[key]).__name__}")
    if not errs:
        if not obj["name"]:
            errs.append("empty name")
        if obj["ph"] != "X":
            errs.append(f"ph {obj['ph']!r} != 'X'")
        if obj["ts"] < 0:
            errs.append("negative ts")
        if obj["dur"] < 0:
            errs.append("negative dur")
    if "args" in obj and not isinstance(obj.get("args"), dict):
        errs.append("args is not an object")
    return errs


def validate_file(path: str) -> list[str]:
    """All schema errors in a trace file, prefixed with line numbers
    ([] = valid). An empty file is an error: a power run with tracing
    enabled must emit at least one event."""
    errors = []
    n = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            n += 1
            try:
                obj = json.loads(line)
            except ValueError as exc:
                errors.append(f"line {lineno}: not JSON ({exc})")
                continue
            for e in validate_event(obj):
                errors.append(f"line {lineno}: {e}")
    if n == 0:
        errors.append("no events: file is empty")
    return errors


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: check_trace_schema.py TRACE_JSONL")
        return 2
    errors = validate_file(argv[0])
    for e in errors:
        print(e)
    print(f"{'FAIL' if errors else 'OK'}: {len(errors)} schema error(s) "
          f"in {argv[0]}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
