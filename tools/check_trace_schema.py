"""CI gate for the observability JSON the engine emits: the Chrome
trace-event JSONL (``NDS_TPU_TRACE``, nds_tpu/obs/trace.py) and the
per-query BenchReport summaries (utils/report.py) the run-analysis
layer (obs/analyze.py, tools/ndsreport.py) consumes. Every documented
shape is validated here so downstream consumers — Perfetto after
array-wrapping, ndsreport, or anything parsing the files directly —
never meet a malformed record.

Trace event schema (one event per line):
  name: non-empty str      ph:  "X" (complete) or "C" (counter)
  cat:  str                ts:  number >= 0 (microseconds)
  pid:  int                tid: int
  args: object (optional)
  "X" events additionally require dur: number >= 0; "C" counter
  events (obs/trace.counter_event — the device-memory lanes) carry no
  dur and require a non-empty all-numeric args object instead.

BenchReport summary schema (``--summary``, README "Observability"):
  query/queryStatus/queryTimes/startTime/env required; optional blocks
  — spans (name/dur_ms/attrs/children tree), metrics (counters/gauges/
  histograms with count+sum and optional p50/p95/p99), memory
  (device_hwm_bytes + source), retries / retry_backoff_s /
  gave_up_reason / deadline_exceeded, the scheduling fields
  placement / reschedules / ladder / promoted_back / governed
  (engine/scheduler.py; README "Placement & degradation"), the resume
  fields incarnation / result_digest and the torn-state degradations
  block (resilience/journal.py; README "Preemption & resume"), and the
  plan-cache block cache (hits + misses required ints; optional
  errors / bytes_read / bytes_written / load_ms — nds_tpu/cache/;
  README "Plan cache"), the kernel-use block kernels (kernel
  name -> positive use count — engine/kernels.py; README "Kernels &
  roofline"), the XLA-capture block profile (path + trigger from the
  obs/profile.py trigger vocabulary, optional bytes), the
  flight-recorder pointer flight (path + optional reason/entries —
  obs/fleet.py; README "Fleet & profiling"), the compiler-cost block
  cost (flops/bytes_accessed/transcendentals sums + a positive
  programs census; optional memory maxima / platform / ops_est
  cross-check — obs/costs.py; README "Cost ledger & telemetry"), and
  the device-memory time-series block telemetry (samples/interval_ms
  + the hbm min/max/mean/series summary — obs/telemetry.py).

Exit 0 when every record validates; prints each offense otherwise.
Run by tests/test_observability.py and tools/static_checks.py as a
tier-1 gate.
"""

from __future__ import annotations

import json
import sys

REQUIRED = {
    "name": str,
    "cat": str,
    "ph": str,
    "ts": (int, float),
    "pid": int,
    "tid": int,
}


def validate_event(obj: object) -> list[str]:
    """Schema errors for one parsed event ([] = valid). Two phases
    are legal: "X" complete events (non-negative dur required) and
    "C" counter events (no dur; a non-empty all-numeric args object
    is the payload — obs/trace.counter_event)."""
    errs = []
    if not isinstance(obj, dict):
        return [f"event is {type(obj).__name__}, not an object"]
    for key, typ in REQUIRED.items():
        if key not in obj:
            errs.append(f"missing key {key!r}")
        elif not isinstance(obj[key], typ) or isinstance(obj[key], bool):
            errs.append(f"{key!r} has type {type(obj[key]).__name__}")
    if not errs:
        if not obj["name"]:
            errs.append("empty name")
        if obj["ts"] < 0:
            errs.append("negative ts")
        if obj["ph"] == "X":
            dur = obj.get("dur")
            if not _num(dur):
                errs.append(f"bad dur {dur!r}")
            elif dur < 0:
                errs.append("negative dur")
        elif obj["ph"] == "C":
            cargs = obj.get("args")
            if (not isinstance(cargs, dict) or not cargs
                    or any(not _num(v) for v in cargs.values())):
                errs.append(f"counter event needs non-empty numeric "
                            f"args, got {cargs!r}")
        else:
            errs.append(f"ph {obj['ph']!r} not in ('X', 'C')")
    if "args" in obj and not isinstance(obj.get("args"), dict):
        errs.append("args is not an object")
    return errs


def validate_file(path: str) -> list[str]:
    """All schema errors in a trace file, prefixed with line numbers
    ([] = valid). An empty file is an error: a power run with tracing
    enabled must emit at least one event."""
    errors = []
    n = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            n += 1
            try:
                obj = json.loads(line)
            except ValueError as exc:
                errors.append(f"line {lineno}: not JSON ({exc})")
                continue
            for e in validate_event(obj):
                errors.append(f"line {lineno}: {e}")
    if n == 0:
        errors.append("no events: file is empty")
    return errors


_STATUS_VOCAB = {"Completed", "CompletedWithTaskFailures", "Failed"}
_HWM_SOURCES = {"device", "accounted"}
# obs/profile.py TRIGGERS — duplicated by value, not imported: this
# validator must stay runnable standalone with no package import
_PROFILE_TRIGGERS = {"query", "slow", "stall", "stream"}


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _validate_span_tree(node: object, path: str) -> list[str]:
    if not isinstance(node, dict):
        return [f"{path}: span node is {type(node).__name__}"]
    errs = []
    if not node.get("name") or not isinstance(node.get("name"), str):
        errs.append(f"{path}: missing/empty span name")
    if not _num(node.get("dur_ms")) or node.get("dur_ms", 0) < 0:
        errs.append(f"{path}: bad dur_ms {node.get('dur_ms')!r}")
    if "attrs" in node and not isinstance(node["attrs"], dict):
        errs.append(f"{path}: attrs is not an object")
    kids = node.get("children", [])
    if not isinstance(kids, list):
        errs.append(f"{path}: children is not a list")
        kids = []
    for i, k in enumerate(kids):
        errs.extend(_validate_span_tree(k, f"{path}.children[{i}]"))
    return errs


def validate_summary(obj: object) -> list[str]:
    """Schema errors for one BenchReport summary dict ([] = valid)."""
    if not isinstance(obj, dict):
        return [f"summary is {type(obj).__name__}, not an object"]
    errs = []
    if not isinstance(obj.get("query"), str) or not obj.get("query"):
        errs.append("missing/empty 'query'")
    status = obj.get("queryStatus")
    if (not isinstance(status, list) or not status
            or any(s not in _STATUS_VOCAB for s in status)):
        errs.append(f"bad queryStatus {status!r}")
    times = obj.get("queryTimes")
    if (not isinstance(times, list) or not times
            or any(not _num(t) or t < 0 for t in times)):
        errs.append(f"bad queryTimes {times!r}")
    if not isinstance(obj.get("startTime"), int):
        errs.append("missing/invalid startTime")
    if not isinstance(obj.get("env"), dict):
        errs.append("missing env object")
    if "spans" in obj:
        errs.extend(_validate_span_tree(obj["spans"], "spans"))
    m = obj.get("metrics", {})
    if not isinstance(m, dict):
        errs.append("metrics is not an object")
    else:
        for block in ("counters", "gauges"):
            vals = m.get(block, {})
            if not isinstance(vals, dict) or any(
                    not _num(v) for v in vals.values()):
                errs.append(f"metrics.{block} has non-numeric values")
        for name, h in (m.get("histograms") or {}).items():
            if (not isinstance(h, dict) or not _num(h.get("count"))
                    or not _num(h.get("sum"))):
                errs.append(f"metrics.histograms[{name!r}] lacks "
                            f"numeric count/sum")
            elif any(k in h and not _num(h[k])
                     for k in ("p50", "p95", "p99")):
                errs.append(f"metrics.histograms[{name!r}] has "
                            f"non-numeric percentile")
    mem = obj.get("memory")
    if mem is not None:
        if (not isinstance(mem, dict)
                or not isinstance(mem.get("device_hwm_bytes"), int)
                or mem["device_hwm_bytes"] < 0
                or mem.get("source") not in _HWM_SOURCES):
            errs.append(f"bad memory block {mem!r}")
    # serving-layer fields (nds_tpu/serve/): tenant attribution on
    # per-request summaries; stale_device_times marks banked (not
    # freshly measured) numbers — a bool that must never be false-y
    # noise
    if "tenant" in obj and (not isinstance(obj["tenant"], str)
                            or not obj["tenant"]):
        errs.append(f"bad tenant {obj.get('tenant')!r}")
    # fleet serving (nds_tpu/serve/fleet.py): which replica answered
    if "replica" in obj and (not isinstance(obj["replica"], str)
                             or not obj["replica"]):
        errs.append(f"bad replica {obj.get('replica')!r}")
    if "stale_device_times" in obj and obj["stale_device_times"] \
            is not True:
        errs.append(f"bad stale_device_times "
                    f"{obj['stale_device_times']!r}")
    if "retries" in obj and (not isinstance(obj["retries"], int)
                             or obj["retries"] < 0):
        errs.append(f"bad retries {obj['retries']!r}")
    if "retry_backoff_s" in obj and (
            not _num(obj["retry_backoff_s"])
            or obj["retry_backoff_s"] < 0):
        errs.append(f"bad retry_backoff_s {obj['retry_backoff_s']!r}")
    if "deadline_exceeded" in obj and not isinstance(
            obj["deadline_exceeded"], bool):
        errs.append("deadline_exceeded is not a bool")
    # scheduling fields (engine/scheduler.py; README "Placement &
    # degradation"): placement + reschedules travel together,
    # ladder only appears on rescheduled queries
    if "placement" in obj and (
            not isinstance(obj["placement"], str)
            or not obj["placement"]):
        errs.append(f"bad placement {obj.get('placement')!r}")
    if "reschedules" in obj and (
            not isinstance(obj["reschedules"], int)
            or obj["reschedules"] < 0):
        errs.append(f"bad reschedules {obj['reschedules']!r}")
    if "ladder" in obj and (
            not isinstance(obj["ladder"], list)
            or not all(isinstance(x, str) for x in obj["ladder"])):
        errs.append(f"bad ladder {obj['ladder']!r}")
    if "promoted_back" in obj and obj["promoted_back"] is not True:
        errs.append(f"bad promoted_back {obj['promoted_back']!r}")
    if "governed" in obj and obj["governed"] is not True:
        # memory-governor pre-admission demotion
        # (engine/scheduler.MemoryGovernor)
        errs.append(f"bad governed {obj['governed']!r}")
    if "prefetch_depth" in obj and (
            not isinstance(obj["prefetch_depth"], int)
            or isinstance(obj["prefetch_depth"], bool)
            or obj["prefetch_depth"] < 0):
        # governor depth admission lowered the phase-A prefetch depth
        # for this query (engine/pipeline_io.py)
        errs.append(f"bad prefetch_depth {obj['prefetch_depth']!r}")
    # resume fields (resilience/journal.QueryJournal; README
    # "Preemption & resume"): which incarnation served the query and
    # the result's content digest
    if "incarnation" in obj and (
            not isinstance(obj["incarnation"], int)
            or isinstance(obj["incarnation"], bool)
            or obj["incarnation"] < 0):
        errs.append(f"bad incarnation {obj['incarnation']!r}")
    if "result_digest" in obj and (
            not isinstance(obj["result_digest"], str)
            or not obj["result_digest"]):
        errs.append(f"bad result_digest {obj['result_digest']!r}")
    # torn-state degradations surfaced per summary
    # (journal_resets_total / snapshot_resets_total)
    deg = obj.get("degradations")
    if deg is not None:
        if (not isinstance(deg, dict) or not deg
                or not set(deg) <= {"journal_resets",
                                    "snapshot_resets"}
                or any(not isinstance(v, int)
                       or isinstance(v, bool) or v <= 0
                       for v in deg.values())):
            errs.append(f"bad degradations block {deg!r}")
    # plan-cache block (nds_tpu/cache/; README "Plan cache"): hits +
    # misses always travel together; byte counts / errors / load_ms
    # are optional and non-negative
    cache = obj.get("cache")
    if cache is not None:
        if (not isinstance(cache, dict)
                or not isinstance(cache.get("hits"), int)
                or not isinstance(cache.get("misses"), int)
                or cache["hits"] < 0 or cache["misses"] < 0):
            errs.append(f"bad cache block {cache!r}")
        else:
            for k in ("errors", "bytes_read", "bytes_written"):
                if k in cache and (not isinstance(cache[k], int)
                                   or cache[k] < 0):
                    errs.append(f"bad cache.{k} {cache[k]!r}")
            if "load_ms" in cache and (not _num(cache["load_ms"])
                                       or cache["load_ms"] < 0):
                errs.append(f"bad cache.load_ms {cache['load_ms']!r}")
    # kernel-use block (engine/kernels.py; README "Kernels &
    # roofline"): kernel name -> positive trace-time use count
    kern = obj.get("kernels")
    if kern is not None:
        if (not isinstance(kern, dict)
                or not all(isinstance(k, str) and isinstance(v, int)
                           and v > 0 for k, v in kern.items())):
            errs.append(f"bad kernels block {kern!r}")
    # XLA-capture block (obs/profile.py; README "Fleet & profiling"):
    # path + trigger always travel together, bytes is optional
    prof = obj.get("profile")
    if prof is not None:
        if (not isinstance(prof, dict)
                or not isinstance(prof.get("path"), str)
                or not prof.get("path")
                or prof.get("trigger") not in _PROFILE_TRIGGERS):
            errs.append(f"bad profile block {prof!r}")
        elif "bytes" in prof and (not isinstance(prof["bytes"], int)
                                  or isinstance(prof["bytes"], bool)
                                  or prof["bytes"] < 0):
            errs.append(f"bad profile.bytes {prof['bytes']!r}")
    # flight-recorder pointer (obs/fleet.py): the failed query's
    # summary names its post-mortem dump
    flight = obj.get("flight")
    if flight is not None:
        if (not isinstance(flight, dict)
                or not isinstance(flight.get("path"), str)
                or not flight.get("path")):
            errs.append(f"bad flight block {flight!r}")
        else:
            if "reason" in flight and not isinstance(
                    flight["reason"], str):
                errs.append(f"bad flight.reason "
                            f"{flight['reason']!r}")
            if "entries" in flight and (
                    not isinstance(flight["entries"], int)
                    or isinstance(flight["entries"], bool)
                    or flight["entries"] < 0):
                errs.append(f"bad flight.entries "
                            f"{flight['entries']!r}")
    # compiler-cost block (obs/costs.py; README "Cost ledger &
    # telemetry"): the three per-dispatch sums always travel as
    # non-negative numbers next to a positive programs census;
    # memory maxima / platform / ops_est cross-check are optional
    cost = obj.get("cost")
    if cost is not None:
        progs = cost.get("programs") if isinstance(cost, dict) else None
        if (not isinstance(cost, dict)
                or not isinstance(progs, dict) or not progs
                or any(not isinstance(k, str) or not k
                       or not isinstance(v, int)
                       or isinstance(v, bool) or v <= 0
                       for k, v in progs.items())
                or any(not _num(cost.get(k)) or cost[k] < 0
                       for k in ("flops", "bytes_accessed",
                                 "transcendentals"))):
            errs.append(f"bad cost block {cost!r}")
        else:
            for k in ("temp_bytes", "argument_bytes", "output_bytes",
                      "ops_est", "flops_per_op"):
                if k in cost and (not _num(cost[k]) or cost[k] < 0):
                    errs.append(f"bad cost.{k} {cost[k]!r}")
            if "platform" in cost and (
                    not isinstance(cost["platform"], str)
                    or not cost["platform"]):
                errs.append(f"bad cost.platform "
                            f"{cost.get('platform')!r}")
            if "ops_est_drift" in cost and \
                    cost["ops_est_drift"] is not True:
                errs.append(f"bad cost.ops_est_drift "
                            f"{cost['ops_est_drift']!r}")
    # device-memory time-series block (obs/telemetry.py): sample
    # count + interval, with the hbm min/max/mean and the decimated
    # [t_offset_ms, bytes] series
    tel = obj.get("telemetry")
    if tel is not None:
        if (not isinstance(tel, dict)
                or not isinstance(tel.get("samples"), int)
                or isinstance(tel.get("samples"), bool)
                or tel["samples"] <= 0
                or not _num(tel.get("interval_ms"))
                or tel["interval_ms"] <= 0):
            errs.append(f"bad telemetry block {tel!r}")
        else:
            hbm = tel.get("hbm")
            if hbm is not None and (
                    not isinstance(hbm, dict)
                    or any(not _num(hbm.get(k)) or hbm[k] < 0
                           for k in ("min_bytes", "max_bytes",
                                     "mean_bytes"))
                    or not isinstance(hbm.get("series"), list)
                    or not hbm["series"]
                    or any(not isinstance(p, list) or len(p) != 2
                           or not _num(p[0]) or not _num(p[1])
                           for p in hbm["series"])):
                errs.append(f"bad telemetry.hbm block {hbm!r}")
    return errs


def validate_summary_file(path: str) -> list[str]:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as exc:
        return [f"{path}: not JSON ({exc})"]
    return [f"{path}: {e}" for e in validate_summary(obj)]


def validate_flight(obj: object) -> list[str]:
    """Schema errors for one flight-recorder dump
    (``flight-r<rank>.json``, obs/fleet.py): rank/pid/reason/ts
    header, a list of ring entries (query + status + ts, optional
    span tree), and the metrics/heartbeats snapshots."""
    if not isinstance(obj, dict):
        return [f"flight dump is {type(obj).__name__}, not an object"]
    errs = []
    if not isinstance(obj.get("rank"), int) or obj["rank"] < 0:
        errs.append(f"bad rank {obj.get('rank')!r}")
    if not isinstance(obj.get("pid"), int):
        errs.append("missing/invalid pid")
    if not isinstance(obj.get("reason"), str) or not obj.get("reason"):
        errs.append("missing/empty reason")
    if not _num(obj.get("ts")):
        errs.append("missing/invalid ts")
    entries = obj.get("entries")
    if not isinstance(entries, list):
        errs.append(f"entries is {type(entries).__name__}, not a list")
        entries = []
    for i, e in enumerate(entries):
        where = f"entries[{i}]"
        if not isinstance(e, dict):
            errs.append(f"{where}: not an object")
            continue
        if not isinstance(e.get("query"), str) or not e.get("query"):
            errs.append(f"{where}: missing/empty query")
        if e.get("status") not in _STATUS_VOCAB:
            errs.append(f"{where}: bad status {e.get('status')!r}")
        if not _num(e.get("ts")):
            errs.append(f"{where}: missing/invalid ts")
        if "wall_ms" in e and (not _num(e["wall_ms"])
                               or e["wall_ms"] < 0):
            errs.append(f"{where}: bad wall_ms {e['wall_ms']!r}")
        if "spans" in e:
            errs.extend(_validate_span_tree(e["spans"],
                                            f"{where}.spans"))
    if not isinstance(obj.get("metrics"), dict):
        errs.append("missing metrics object")
    if "heartbeats" in obj and not isinstance(obj["heartbeats"], dict):
        errs.append("heartbeats is not an object")
    return errs


def validate_flight_file(path: str) -> list[str]:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as exc:
        return [f"{path}: not JSON ({exc})"]
    return [f"{path}: {e}" for e in validate_flight(obj)]


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) == 2 and argv[0] == "--summary":
        errors = validate_summary_file(argv[1])
        target = argv[1]
    elif len(argv) == 2 and argv[0] == "--flight":
        errors = validate_flight_file(argv[1])
        target = argv[1]
    elif len(argv) == 1:
        errors = validate_file(argv[0])
        target = argv[0]
    else:
        print("usage: check_trace_schema.py [--summary|--flight] FILE")
        return 2
    for e in errors:
        print(e)
    print(f"{'FAIL' if errors else 'OK'}: {len(errors)} schema error(s) "
          f"in {target}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
