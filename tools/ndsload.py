"""Seeded load generator for the query server (README "Serving").

Builds a deterministic stream of literal-variant NDS + NDS-H requests
(the suites' own seeded parameter generators — dsqgen/qgen `-rngseed`
semantics) spread across tenants, and drives a server either in-process
(`run_inproc`) or over the TCP JSON-lines front (`run_tcp`). A load is
three phases:

  warmup   every (suite, template) once, sequentially — pays the
           compile/cache-load cost outside the timed window
  load     N requests at a given concurrency: mixed templates, mixed
           tenants, every instance a fresh literal draw
  burst    optional oversubscription spike (fire `burst` requests at
           once) to prove brownout sheds instead of collapsing

The report carries per-phase status counts, latency quantiles
(p50/p95/p99 over the load phase), and the engine metric deltas the
acceptance gates read (compiles_total, compile_cache_misses_total,
server_shed_total). Multi-statement templates (NDS 14/23/24/39 parts,
NDS-H q15's view lifecycle) are excluded: a serving request is one
statement by contract.

CLI (standalone, against a running TCP server):

  python tools/ndsload.py --host 127.0.0.1 --port 9321 \
      --requests 64 --concurrency 8 --tenants 4 --seed 7

Fleet mode (README "Serve fleet") spins a supervised replica fleet up
in-process and drives it through the FleetRouter, with an optional
SEEDED chaos schedule — kill/drain specific replicas at specific
offsets into the load phase, reproducibly, from the CLI:

  python tools/ndsload.py --fleet 3 --requests 64 --concurrency 16 \
      --kill replica=1@2.0,KILL --kill replica=2@3.5,TERM

The final report gains a per-replica breakdown (request counts,
status mix, latency quantiles per ring member) plus the router
journal's zero-loss/zero-double verification.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import re
import signal as _signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# single-statement serving defaults: small, planner-fast templates from
# each suite (serve_check narrows further)
DEFAULT_NDS_H = (1, 5, 6)
DEFAULT_NDS = (7, 96, 93)

MULTIPART_NDS = {14, 23, 24, 39}

# every base table the default NDS serving templates (and their
# literal variants) scan — the fleet/gen warehouse table list
# (serve_check and fleet_serve_check generate exactly these)
GEN_NDS_TABLES = ("store_sales", "store_returns", "date_dim", "store",
                  "customer", "customer_address",
                  "customer_demographics", "household_demographics",
                  "item", "promotion", "reason", "time_dim")


def render(suite: str, template: int, rng: random.Random) -> str:
    """One fresh literal-variant statement of a template."""
    if suite == "nds_h":
        from nds_tpu.nds_h import streams as hs
        if template == 15:
            raise ValueError("q15 (view lifecycle) is not servable as "
                             "one statement")
        return hs.render_query(
            template, hs.random_params(template, rng, 0))
    from nds_tpu.nds import streams as ds
    if template in MULTIPART_NDS:
        raise ValueError(f"NDS q{template} is multi-statement")
    sql = ds.render_query(
        template, ds.random_params(template, rng, 0))
    stmts = [s.strip() for s in sql.split(";") if s.strip()]
    if len(stmts) != 1:
        raise ValueError(f"NDS q{template} rendered {len(stmts)} "
                         f"statements")
    return stmts[0]


def build_requests(count: int, seed: int, tenants: int = 2,
                   nds_h_templates=DEFAULT_NDS_H,
                   nds_templates=DEFAULT_NDS) -> list:
    """Deterministic request docs: round-robin over the mixed template
    pool, fresh literal draw per instance, tenants interleaved."""
    rng = random.Random(seed)
    pool = ([("nds_h", t) for t in nds_h_templates]
            + [("nds", t) for t in nds_templates])
    docs = []
    for i in range(count):
        suite, tpl = pool[i % len(pool)]
        docs.append({
            "tenant": f"tenant{i % max(1, tenants)}",
            "suite": suite,
            "qname": f"{suite}-q{tpl}#{i}",
            "sql": render(suite, tpl, rng),
        })
    return docs


def warmup_docs(seed: int, nds_h_templates=DEFAULT_NDS_H,
                nds_templates=DEFAULT_NDS) -> list:
    rng = random.Random(seed * 7919 + 1)
    return ([{"tenant": "warmup", "suite": "nds_h",
              "qname": f"warm-h{t}",
              "sql": render("nds_h", t, rng)}
             for t in nds_h_templates]
            + [{"tenant": "warmup", "suite": "nds",
                "qname": f"warm-d{t}",
                "sql": render("nds", t, rng)}
               for t in nds_templates])


def _quantiles(samples: list) -> dict:
    # the analyzer's nearest-rank implementation: load-generator and
    # ndsreport quantiles must agree when read side by side
    from nds_tpu.obs.analyze import _quantiles as q
    return q(samples)


def summarize(responses: list) -> dict:
    by_status: dict = {}
    shed_reasons: dict = {}
    lat = []
    for r in responses:
        by_status[r.get("status", "?")] = by_status.get(
            r.get("status", "?"), 0) + 1
        if r.get("status") == "ok":
            lat.append(float(r.get("elapsed_ms", 0.0)))
        elif r.get("status") == "shed":
            # reason class only (strip the :detail tail): the report
            # distinguishes queue-depth vs deadline vs governor sheds
            why = str(r.get("shed_reason", "?")).split(":")[0]
            shed_reasons[why] = shed_reasons.get(why, 0) + 1
    out = {"responses": len(responses), "status": by_status,
           "latency_ms": _quantiles(lat)}
    if shed_reasons:
        out["shed_reasons"] = shed_reasons
    reps: dict = {}
    for r in responses:
        rep = r.get("replica")
        if rep is None:
            continue
        b = reps.setdefault(rep, {"count": 0, "status": {}, "lat": []})
        b["count"] += 1
        st = r.get("status", "?")
        b["status"][st] = b["status"].get(st, 0) + 1
        if st == "ok":
            b["lat"].append(float(r.get("elapsed_ms", 0.0)))
    if reps:
        # per-replica breakdown: which ring member answered what, and
        # how fast — the fleet failover report's core table
        out["replicas"] = {
            name: {"count": b["count"], "status": b["status"],
                   "latency_ms": _quantiles(b["lat"])}
            for name, b in sorted(reps.items())}
    return out


# ------------------------------------------------------------ drivers

def run_inproc(server, docs: list, concurrency: int = 8) -> list:
    """Drive an in-process QueryServer: submit with at most
    ``concurrency`` outstanding futures (the client-side window; the
    server's own queue depth is what brownout watches)."""
    out = []
    window: list = []
    for doc in docs:
        window.append(server.submit(doc["tenant"], doc["suite"],
                                    doc["sql"], doc["qname"]))
        if len(window) >= concurrency:
            out.append(_resp_doc(window.pop(0).result(timeout=600)))
    for fut in window:
        out.append(_resp_doc(fut.result(timeout=600)))
    return out


def burst_inproc(server, docs: list) -> list:
    """Fire every doc at once (no client window): the overload spike
    the brownout gate wants."""
    futs = [server.submit(d["tenant"], d["suite"], d["sql"],
                          d["qname"]) for d in docs]
    return [_resp_doc(f.result(timeout=600)) for f in futs]


def _resp_doc(resp) -> dict:
    import dataclasses
    return {k: v for k, v in dataclasses.asdict(resp).items()
            if v is not None}


def run_tcp(host: str, port: int, docs: list,
            concurrency: int = 8) -> list:
    from nds_tpu.serve.net import request_many
    return asyncio.run(request_many(host, port, docs, concurrency))


# -------------------------------------------------------------- fleet

async def run_router(router, docs: list, concurrency: int = 8) -> list:
    """Drive a FleetRouter with at most ``concurrency`` requests in
    flight (call inside the router's event loop)."""
    sem = asyncio.Semaphore(max(1, concurrency))

    async def one(doc):
        async with sem:
            return await router.submit(doc)

    return list(await asyncio.gather(*[one(d) for d in docs]))


def parse_kill_schedule(specs) -> list:
    """``replica=<idx-or-name>@<t>[,<signal>]`` specs -> sorted event
    list (signal defaults to KILL; TERM drains). Offsets are seconds
    into the load phase, so a schedule replays deterministically."""
    out = []
    for spec in specs or []:
        m = re.match(r"replica=([\w-]+)@([0-9.]+)(?:,(\w+))?$",
                     str(spec))
        if not m:
            raise ValueError(
                f"bad --kill spec {spec!r} "
                f"(want replica=N@t[,signal])")
        target, t, signame = m.groups()
        s = (signame or "KILL").upper()
        if not s.startswith("SIG"):
            s = f"SIG{s}"
        try:
            signum = getattr(_signal, s)
        except AttributeError as exc:
            raise ValueError(f"unknown signal {signame!r} in "
                             f"{spec!r}") from exc
        out.append({"replica": target, "t": float(t),
                    "signal": int(signum), "signame": s})
    return sorted(out, key=lambda e: e["t"])


async def run_chaos(supervisor, schedule: list, names: list) -> list:
    """Deliver a parsed kill schedule against a running fleet
    (numeric targets index ``names``). Returns the fired events."""
    t0 = time.monotonic()
    fired = []
    for ev in schedule:
        delay = ev["t"] - (time.monotonic() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        name = (names[int(ev["replica"])]
                if str(ev["replica"]).isdigit() else ev["replica"])
        print(f"[chaos] t={ev['t']:g}s {ev['signame']} -> {name}",
              flush=True)
        supervisor.kill(name, ev["signal"])
        fired.append({**ev, "replica": name})
    return fired


def fleet_replica_argv(workdir: str, gen_scale: float,
                       max_queue: int = 16,
                       boundary: "str | None" = None):
    """argv factory for gen-warehouse replicas (fleet mode + gate +
    tests share one launch recipe)."""
    def replica_argv(name, announce, _inc):
        argv = [sys.executable, "-m", "nds_tpu.serve.replica",
                "--name", name, "--announce", announce,
                "--gen_scale", str(gen_scale),
                "--gen_nds_tables", ",".join(GEN_NDS_TABLES),
                "--backend", "tpu",
                "--cache_dir", os.path.join(workdir, "plancache"),
                "--summary_dir", os.path.join(workdir, "serve_json"),
                "--max_queue", str(max_queue),
                "--property", "engine.retry.base_delay_s=0.01"]
        if boundary is not None:
            argv += ["--property",
                     f"engine.prefetch.boundary={boundary}"]
        return argv
    return replica_argv


def run_fleet(args, h_tpls, d_tpls) -> int:
    """--fleet mode: supervised replicas + router in-process, seeded
    load + seeded chaos, per-replica report + journal verdict."""
    import tempfile

    from nds_tpu.serve.fleet import launch_fleet
    from nds_tpu.utils.config import EngineConfig

    schedule = parse_kill_schedule(args.kill)
    names = [f"r{i}" for i in range(args.fleet)]
    with tempfile.TemporaryDirectory(prefix="ndsload_fleet_") as wd:
        cfg = EngineConfig(overrides={
            "serve.max_queue": str(args.max_queue),
            "serve.fleet.ping_interval_s": "0.25",
            "serve.fleet.ping_timeout_s": "3",
        })
        sup, router = launch_fleet(
            os.path.join(wd, "fleet"), names,
            fleet_replica_argv(wd, args.gen_scale, args.max_queue),
            config=cfg, stall_s=args.stall_s)
        sup.start()
        report: dict = {"seed": args.seed, "fleet": names}

        async def drive():
            await router.start()
            if not await router.wait_admitted(args.fleet, 300):
                raise RuntimeError(
                    f"fleet never formed: healthy="
                    f"{router.healthy_replicas()}")
            t0 = time.monotonic()
            w = await run_router(
                router, warmup_docs(args.seed, h_tpls, d_tpls), 1)
            report["warmup"] = {
                **summarize(w),
                "wall_s": round(time.monotonic() - t0, 3)}
            docs = build_requests(args.requests, args.seed,
                                  args.tenants, h_tpls, d_tpls)
            t0 = time.monotonic()
            results = await asyncio.gather(
                run_chaos(sup, schedule, names),
                run_router(router, docs, args.concurrency))
            report["chaos"] = results[0]
            report["load"] = {
                **summarize(results[1]),
                "wall_s": round(time.monotonic() - t0, 3)}
            report["journal"] = router.journal.verify()
            await router.stop()

        try:
            asyncio.run(drive())
        finally:
            report["supervisor"] = sup.stop()
        print(json.dumps(report, indent=2))
        ok = report.get("load", {}).get("status", {}).get("ok", 0)
        j = report.get("journal", {})
        clean = not j.get("lost") and not j.get("double")
        return 0 if (ok == args.requests and clean) else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None,
                    help="TCP server to drive (omit with --fleet)")
    ap.add_argument("--fleet", type=int, default=0,
                    help="spin up N supervised gen-warehouse replicas "
                         "+ router in-process and drive those instead "
                         "of --port")
    ap.add_argument("--kill", action="append", default=[],
                    help="chaos event replica=<idx-or-name>@<t>"
                         "[,signal], seconds into the load phase "
                         "(repeatable; fleet mode only)")
    ap.add_argument("--gen_scale", type=float, default=0.01,
                    help="fleet-mode warehouse scale factor")
    ap.add_argument("--max_queue", type=int, default=16,
                    help="fleet-mode per-replica queue bound")
    ap.add_argument("--stall_s", type=float, default=10.0,
                    help="fleet-mode watchdog stall budget")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--tenants", type=int, default=2)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--burst", type=int, default=0,
                    help="extra simultaneous overload requests after "
                         "the load phase")
    ap.add_argument("--warmup", action="store_true",
                    help="run the one-per-template warmup phase first")
    ap.add_argument("--nds_h_templates",
                    default=",".join(map(str, DEFAULT_NDS_H)),
                    help="comma list of NDS-H templates ('' = none)")
    ap.add_argument("--nds_templates",
                    default=",".join(map(str, DEFAULT_NDS)),
                    help="comma list of NDS templates ('' = none)")
    args = ap.parse_args(argv)
    h_tpls = tuple(int(x) for x in args.nds_h_templates.split(",")
                   if x.strip())
    d_tpls = tuple(int(x) for x in args.nds_templates.split(",")
                   if x.strip())
    if not h_tpls and not d_tpls:
        ap.error("template pool is empty")
    if args.fleet:
        return run_fleet(args, h_tpls, d_tpls)
    if args.port is None:
        ap.error("--port is required without --fleet")
    if args.kill:
        ap.error("--kill needs --fleet (a bare TCP server has no "
                 "supervisor to deliver signals)")

    report: dict = {"seed": args.seed}
    if args.warmup:
        t0 = time.monotonic()
        w = run_tcp(args.host, args.port,
                    warmup_docs(args.seed, h_tpls, d_tpls), 1)
        report["warmup"] = {**summarize(w),
                            "wall_s": round(time.monotonic() - t0, 3)}
    docs = build_requests(args.requests, args.seed, args.tenants,
                          h_tpls, d_tpls)
    t0 = time.monotonic()
    responses = run_tcp(args.host, args.port, docs, args.concurrency)
    report["load"] = {**summarize(responses),
                      "wall_s": round(time.monotonic() - t0, 3)}
    if args.burst:
        bdocs = build_requests(args.burst, args.seed + 1, args.tenants,
                               h_tpls, d_tpls)
        burst = run_tcp(args.host, args.port, bdocs,
                        concurrency=args.burst)
        report["burst"] = summarize(burst)
    print(json.dumps(report, indent=2))
    ok = report["load"]["status"].get("ok", 0)
    return 0 if ok == args.requests else 1


if __name__ == "__main__":
    sys.exit(main())
