"""Columnar compression gate: encoded == raw results, fewer bytes.

tier-1 (via tools/static_checks.py) proves the compressed
device-resident columnar store (nds_tpu/columnar/; README "Compressed
columnar store") end-to-end on the CPU backend:

1. **power-stream parity + bytes** — a 3-query NDS-H power stream
   (q1/q3/q6: string group keys, date-range filters, a 3-way join)
   runs on the device placement twice — ``columnar.encode=off`` then
   ``=auto`` — over the same generated warehouse. The gate asserts
   every query Completed in both runs, result rows are IDENTICAL, the
   encoded run's measured ``bytes_scanned`` never exceeds the raw
   run's, at least one query's drops >= 2x (the ROADMAP item 4
   acceptance shape), and every encoded summary carries a
   ``compression_ratio``.
2. **manifest round-trip** — a table cached via
   ``io/table_cache.save_table`` under an active mode records its
   per-column encoding specs in ``_manifest.json``; a fresh
   ``load_table`` restores EXACTLY those specs (seeded memo, no
   re-derivation), and a mode change invalidates them.

The suite-level compression ratio prints for the record (the real-chip
acceptance — SF3 NDS-H device-resident where SF1 was the ceiling —
scales from the same per-table ratios).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCALE = 0.01
TEMPLATES = (1, 3, 6)


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _write_stream(path: str) -> None:
    from nds_tpu.nds_h import streams as hstreams
    parts = [f"-- Template file: {qn}\n\n"
             f"{hstreams.render_query(qn, None, stream=0)}\n"
             for qn in TEMPLATES]
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write("\n".join(parts))


def _summaries(jsons: str) -> dict:
    out = {}
    for name in os.listdir(jsons):
        with open(os.path.join(jsons, name)) as f:
            s = json.load(f)
        if isinstance(s, dict) and "query" in s and "queryStatus" in s:
            out[s["query"]] = s
    return out


def _run_stream(workdir: str, raw: str, stream: str,
                label: str, encode: str) -> "dict | None":
    from nds_tpu.nds_h.power import SUITE
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    jsons = os.path.join(workdir, f"json_{label}")
    out = os.path.join(workdir, f"rows_{label}")
    cfg = EngineConfig(overrides={
        "engine.backend": "tpu",          # device placement on the
        "columnar.encode": encode,        # local CPU jax backend
    })
    failures = power_core.run_query_stream(
        SUITE, raw, stream, os.path.join(workdir, f"{label}.csv"),
        config=cfg, input_format="raw", json_summary_folder=jsons,
        output_prefix=out)
    if failures:
        print(f"FAIL: {failures} query failure(s) in the {label} run")
        return None
    return {"summaries": _summaries(jsons), "rows": out}


def run_power_parity(workdir: str) -> int:
    from nds_tpu.io.result_io import read_result
    from nds_tpu.nds_h import gen_data
    raw = os.path.join(workdir, "raw")
    stream = os.path.join(workdir, "streams", "stream.sql")
    gen_data.generate_data_local(SCALE, 2, raw, workers=2)
    _write_stream(stream)
    base = _run_stream(workdir, raw, stream, "rawrun", "off")
    if base is None:
        return 1
    enc = _run_stream(workdir, raw, stream, "encoded", "auto")
    if enc is None:
        return 1
    best = 0.0
    for qn in TEMPLATES:
        q = f"query{qn}"
        b, e = base["summaries"].get(q), enc["summaries"].get(q)
        if not b or not e:
            return _fail(f"{q} summary missing")
        rb = read_result(os.path.join(base["rows"], q))
        re_ = read_result(os.path.join(enc["rows"], q))
        if rb is None or re_ is None:
            return _fail(f"{q} result rows missing on disk")
        if not rb.equals(re_):
            return _fail(f"{q} rows differ between raw and encoded")
        bs_b = (b.get("engineTimings") or {}).get("bytes_scanned")
        bs_e = (e.get("engineTimings") or {}).get("bytes_scanned")
        if not bs_b or not bs_e:
            return _fail(f"{q} missing bytes_scanned "
                         f"(raw={bs_b!r} enc={bs_e!r})")
        if bs_e > bs_b:
            return _fail(f"{q} encoded run scanned MORE bytes "
                         f"({bs_e:.0f} > {bs_b:.0f})")
        ratio = (e.get("engineTimings") or {}).get("compression_ratio")
        if not ratio or ratio < 1.0:
            return _fail(f"{q} encoded summary lacks a sane "
                         f"compression_ratio ({ratio!r})")
        drop = bs_b / bs_e
        best = max(best, drop)
        print(f"  {q}: bytes {bs_b:.0f} -> {bs_e:.0f} "
              f"({drop:.2f}x drop, ratio {ratio:.2f})")
    if best < 2.0:
        return _fail(f"no query dropped bytes_scanned >= 2x "
                     f"(best {best:.2f}x)")
    print(f"OK: power parity — rows identical, best bytes drop "
          f"{best:.2f}x across {len(TEMPLATES)} queries")
    return 0


def run_manifest_roundtrip(workdir: str) -> int:
    from nds_tpu import columnar
    from nds_tpu.datagen import tpch as gen_h
    from nds_tpu.io import table_cache
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds_h.schema import get_schemas
    cache_dir = os.path.join(workdir, "tcache")
    schema = get_schemas()["orders"]
    table = from_arrays("orders", schema,
                        gen_h.gen_table("orders", SCALE))
    columnar.set_mode("auto")
    try:
        specs = columnar.table_specs(table)
        encoded = {n: s for n, s in specs.items() if s is not None}
        if not encoded:
            return _fail("orders planned no encodings under auto")
        table_cache.save_table(cache_dir, table)
        loaded = table_cache.load_table(cache_dir, "orders", schema)
        if loaded is None:
            return _fail("cached orders failed to load back")
        specs2 = columnar.table_specs(loaded)
        if specs2 != specs:
            return _fail(f"specs did not round-trip: {specs2} != "
                         f"{specs}")
        comp = columnar.table_compression(loaded)
        if comp["ratio"] <= 1.0:
            return _fail(f"orders table compression <= 1x: {comp}")
        print(f"OK: manifest round-trip — {len(encoded)} encoded "
              f"column(s), table ratio {comp['ratio']:.2f}x")
    finally:
        columnar.set_mode(None)
    # a DIFFERENT mode must reject the persisted specs (stale-metadata
    # guard), not silently decode with them
    columnar.set_mode("rle")
    try:
        if columnar.manifest_encodings(cache_dir, "orders") is not None:
            return _fail("mode change did not invalidate persisted "
                         "encoding metadata")
    finally:
        columnar.set_mode(None)
    print("OK: mode-change invalidation of persisted encodings")
    return 0


def main(argv=None) -> int:
    with tempfile.TemporaryDirectory(prefix="nds_compress_") as wd:
        for name, fn in (("power-parity", run_power_parity),
                         ("manifest", run_manifest_roundtrip)):
            print(f"-- compress_check: {name} --")
            rc = fn(wd)
            if rc:
                return rc
    print("COMPRESS CHECK OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
