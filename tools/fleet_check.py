"""Fleet observability gate: a REAL 2-process NDS-H power run on a
virtual mesh, asserted end-to-end.

tier-1 (via tools/static_checks.py) launches two OS processes — each
with 2 virtual CPU devices, joined into one jax.distributed world —
running the NDS-H power driver path (``power_core.run_query_stream``,
``--backend distributed``) over a tiny raw warehouse, with:

- **artificially skewed clocks** (30 s apart): the fleet clock
  handshake (obs/fleet.py) must measure the skew, each rank must
  write its own ``trace-r<rank>.jsonl`` shard + ``fleet-r<rank>.json``
  sidecar, and ``ndsreport analyze`` must merge the shards into ONE
  clock-aligned timeline — paired per-rank query spans overlap after
  alignment (they are 30 s apart before), the attribution table
  carries the ``straggler_wait`` column, and categories + residual
  still sum to wall-clock by construction;

- **an induced stall** (``stream.query:hang`` at one query, injected
  in BOTH ranks so the SPMD world stays paired, watchdog armed at
  ``stall_s=2``): every rank's watchdog must dump a flight-recorder
  ``flight-r<rank>.json`` that round-trips the flight schema
  (tools/check_trace_schema.py --flight) AND grab an on-demand XLA
  profiler capture, with the stall report pointing at both;

- **a profile trigger** (``engine.profile.mode=query1`` — the first
  query in stream order, so its capture happens before the induced
  stall): the triggered query's BenchReport must carry a nonzero
  ``profile`` block (path on disk, bytes > 0) that validates against
  the summary schema, and the stall's reserved capture path must be
  filled by the first post-stall query (query6 here).

This is the gate behind ROADMAP items 3 and 4: a multi-host run that
stalls or straggles must leave a merged timeline, a post-mortem dump,
and device-level evidence — proven here on every CI run, not first
discovered on a real pod.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import check_trace_schema  # noqa: E402

SKEW_S = 30.0
HANG_QUERY = "query3"
PROFILED_QUERY = "query1"
SCALE = 0.005


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _launch_fleet(workdir: str) -> "list[str] | None":
    """Two power-run ranks over one warehouse; returns their stdouts
    (None on failure, after printing the offender's tail)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = os.path.join(repo, "tests", "_fleet_child.py")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "NDS_TPU_TRACE",
                        "NDS_TPU_FAULTS", "NDS_TPU_PROFILE")}
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    # both ranks hang at the same query: the stall is fleet-wide (the
    # SPMD world stays paired), and every rank's watchdog must leave a
    # post-mortem
    env["NDS_TPU_FAULTS"] = f"stream.query:hang=8@{HANG_QUERY}"
    procs = [subprocess.Popen(
        [sys.executable, child, str(port), str(rank), "2", "2",
         workdir, str(SKEW_S), "power"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=570)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        print("FAIL: fleet children timed out")
        return None
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 or f"FLEET_OK rank={rank}" not in out:
            print(f"FAIL: rank {rank} rc={p.returncode}:\n"
                  f"{out[-4000:]}")
            return None
    return outs


def check_fleet_run(workdir: str) -> int:
    from nds_tpu.nds_h import gen_data, streams
    raw = os.path.join(workdir, "raw")
    sdir = os.path.join(workdir, "streams")
    run_dir = os.path.join(workdir, "run")
    gen_data.generate_data_local(SCALE, 2, raw, workers=2)
    streams.generate_query_streams(sdir, 1)
    if _launch_fleet(workdir) is None:
        return 1

    # 1. per-rank artifacts: trace shards, sidecars, flight dumps
    errors = []
    for rank in range(2):
        for name in (f"trace-r{rank}.jsonl", f"fleet-r{rank}.json",
                     f"flight-r{rank}.json"):
            if not os.path.exists(os.path.join(run_dir, name)):
                errors.append(f"missing {name} in run dir")
    if errors:
        return _fail("; ".join(errors))
    for rank in range(2):
        errs = check_trace_schema.validate_flight_file(
            os.path.join(run_dir, f"flight-r{rank}.json"))
        if errs:
            return _fail(f"flight-r{rank}.json schema: {errs}")
        errs = check_trace_schema.validate_file(
            os.path.join(run_dir, f"trace-r{rank}.jsonl"))
        if errs:
            return _fail(f"trace-r{rank}.jsonl schema: {errs[:5]}")
    with open(os.path.join(run_dir, "fleet-r1.json")) as f:
        side1 = json.load(f)
    if not side1.get("aligned"):
        return _fail(f"rank 1 handshake not aligned: {side1}")
    off = float(side1.get("boot_offset_s", 0.0))
    if abs(off - SKEW_S) > 2.0:
        return _fail(f"rank 1 offset {off:.3f}s should measure the "
                     f"{SKEW_S:.0f}s skew")

    # 2. the induced stall left reports pointing at flight + profile
    stall_docs = []
    for name in sorted(os.listdir(run_dir)):
        if name.startswith("stall-"):
            with open(os.path.join(run_dir, name)) as f:
                stall_docs.append(json.load(f))
    pointed = [d for d in stall_docs
               if d.get("flight") and d.get("profile")]
    if not pointed:
        return _fail(f"no stall report carries flight+profile "
                     f"pointers ({len(stall_docs)} report(s))")
    for key in ("flight", "profile"):
        if not os.path.exists(pointed[0][key]):
            return _fail(f"stall report points at missing {key}: "
                         f"{pointed[0][key]}")

    # 3. the profile-triggered query's BenchReport carries a nonzero
    # profile block (and every summary validates)
    prof_block = None
    from nds_tpu.obs import analyze
    for name in sorted(os.listdir(run_dir)):
        if not name.endswith(".json") or "power-" not in name:
            continue
        if not analyze.is_report_basename(name):
            continue  # the resume journal (<unit>_queries.json)
        path = os.path.join(run_dir, name)
        errs = check_trace_schema.validate_summary_file(path)
        if errs:
            return _fail(f"summary schema: {errs[:5]}")
        with open(path) as f:
            s = json.load(f)
        if s.get("query") == PROFILED_QUERY and "profile" in s:
            prof_block = s["profile"]
    if not prof_block:
        return _fail(f"{PROFILED_QUERY} summary lacks the profile "
                     f"block")
    if prof_block.get("trigger") != "query" \
            or not os.path.isdir(prof_block.get("path", "")) \
            or prof_block.get("bytes", 0) <= 0:
        return _fail(f"profile block should name an on-disk capture "
                     f"with bytes > 0: {prof_block}")

    # 4. ndsreport analyze: one clock-aligned fleet timeline with
    # straggler attribution, invariant intact
    from nds_tpu.obs import analyze
    a = analyze.analyze_run(run_dir)
    fleet = a.get("fleet")
    if not fleet or fleet.get("world") != 2:
        return _fail(f"analysis lacks the 2-rank fleet block: {fleet}")
    for row in a["queries"]:
        total = sum(row["categories"].values()) + row["residual_ms"]
        if abs(total - row["wall_ms"]) > 1e-6:
            return _fail(f"{row['query']}: categories+residual "
                         f"{total:.3f} != wall {row['wall_ms']:.3f}")
        if "straggler_wait" not in row["categories"]:
            return _fail(f"{row['query']}: no straggler_wait category")
    table = analyze.format_attribution(a)
    if "stragl" not in table:
        return _fail("attribution table lacks the straggler column")
    pids = {e.get("pid") for e in a["trace_events"]
            if e.get("name") == "query"}
    if not {0, 1} <= pids:
        return _fail(f"merged timeline should carry both rank lanes, "
                     f"got pids {pids}")
    # alignment: both ranks' spans for the same query overlap (they
    # are SKEW_S apart before alignment)
    spans_by_q: dict = {}
    for e in a["trace_events"]:
        if e.get("name") == "query":
            q = (e.get("args") or {}).get("query")
            spans_by_q.setdefault(q, {})[e["pid"]] = (
                e["ts"], e["ts"] + e.get("dur", 0))
    overlapped, max_gap_us = 0, 0.0
    for q, by_rank in spans_by_q.items():
        if len(by_rank) < 2:
            continue
        (s0, e0), (s1, e1) = by_rank[0], by_rank[1]
        if max(s0, s1) < min(e0, e1):
            overlapped += 1
        max_gap_us = max(max_gap_us, abs(s1 - s0))
    # alignment proof: without the shift the lanes sit SKEW_S apart;
    # aligned they differ only by real scheduling drift. A loaded box
    # can drift a short query past strict overlap — the gap bound is
    # the hard invariant, overlap the common case
    if max_gap_us > (SKEW_S / 2) * 1e6:
        return _fail(f"aligned rank lanes still {max_gap_us / 1e6:.1f}s "
                     f"apart: { {q: sorted(r) for q, r in spans_by_q.items()} }")
    if not overlapped:
        print(f"note: no strict span overlap (max gap "
              f"{max_gap_us / 1e6:.1f}s) — alignment holds via the "
              f"gap bound")
    html = analyze.render_html(a)
    if "Fleet timeline" not in html:
        return _fail("HTML report lacks the fleet timeline")
    print(f"OK: fleet run (2 ranks, {SKEW_S:.0f}s skew aligned, "
          f"{overlapped} paired span(s) overlap, stall -> flight + "
          f"XLA capture, {PROFILED_QUERY} profile block "
          f"{prof_block['bytes']} bytes)")
    return 0


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="nds_fleet_") as workdir:
        return check_fleet_run(workdir)


if __name__ == "__main__":
    sys.exit(main())
