"""ndslint: run the repo's hazard-class lint rules over the tree.

Drives ``nds_tpu/analysis/lint_rules.py`` (rule catalog + waiver
semantics live there; see its docstring for the NDS1xx rule ids).
Configuration comes from ``[tool.ndslint]`` in pyproject.toml:

    roots   = ["nds_tpu", "tools"]   # directories to lint
    exclude = ["query_templates"]    # path substrings to skip
    rules   = []                     # rule-id allowlist ([] = all)

Waivers are per-line and must carry a justification:

    cache[id(plan)] = entry  # ndslint: waive[NDS1xx] -- entry pins plan

Exit 0 when the tree is clean (waived findings print with their notes
under -v); exit 1 on any unwaived violation, malformed waiver, or
stale waiver. ``--waiver-report`` prints the tree-wide waiver-hygiene
report instead (shared with tools/ndsraces.py: per-rule counts for
both tools, stale waivers flagged). Run by tools/static_checks.py as a
tier-1 gate.
"""

from __future__ import annotations

import argparse
import os
import pathlib
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from nds_tpu.analysis import lint_rules  # noqa: E402

DEFAULT_CONFIG = {
    "roots": ["nds_tpu", "tools"],
    "exclude": [],
    "rules": [],
}


def load_section(repo: pathlib.Path, section: str) -> dict:
    """A ``[tool.*]`` table from pyproject.toml, via tomllib/tomli when
    available with a string/string-list fallback parser otherwise (the
    configs use nothing fancier). Shared with tools/ndsraces.py — one
    config grammar for both gates."""
    pp = repo / "pyproject.toml"
    if not pp.exists():
        return {}
    text = pp.read_text()
    data = None
    for mod in ("tomllib", "tomli"):
        try:
            data = __import__(mod).loads(text)
            break
        except ImportError:
            continue
    if data is not None:
        out = data
        for part in section.split("."):
            out = out.get(part, {}) if isinstance(out, dict) else {}
        return dict(out) if isinstance(out, dict) else {}
    # minimal fallback: section header + `key = [...]` string lists
    cfg: dict = {}
    in_section = False
    for line in text.splitlines():
        s = line.strip()
        if s.startswith("["):
            in_section = s == f"[{section}]"
            continue
        if in_section and "=" in s:
            key, _, val = s.partition("=")
            items = [v.strip().strip("\"'")
                     for v in val.strip().strip("[]").split(",")]
            cfg[key.strip()] = [v for v in items if v]
    return cfg


def load_config(repo: pathlib.Path) -> dict:
    """[tool.ndslint] overlaid on the defaults."""
    cfg = dict(DEFAULT_CONFIG)
    cfg.update(load_section(repo, "tool.ndslint"))
    return cfg


def collect_sources(repo: pathlib.Path, cfg: dict) -> "dict[str, str]":
    sources = {}
    for root in cfg["roots"]:
        base = repo / root
        if not base.exists():
            continue
        for p in sorted(base.rglob("*.py")):
            rel = p.relative_to(repo).as_posix()
            if any(x in rel for x in cfg["exclude"]):
                continue
            sources[rel] = p.read_text()
    return sources


def run(repo: pathlib.Path, verbose: bool = False,
        cfg: "dict | None" = None) -> int:
    cfg = load_config(repo) if cfg is None else cfg
    sources = collect_sources(repo, cfg)
    enabled = set(cfg["rules"]) or None
    res = lint_rules.lint_sources(sources, enabled=enabled)
    for v in res.violations + res.errors:
        print(v)
    if verbose:
        for v in res.waived:
            print(f"{v.path}:{v.line}: {v.rule} waived -- "
                  f"{v.waiver_note}")
    bad = len(res.violations) + len(res.errors)
    print(f"{'FAIL' if bad else 'OK'}: {bad} violation(s), "
          f"{len(res.waived)} waived, {len(sources)} file(s)")
    return 1 if bad else 0


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print waived findings with their notes")
    ap.add_argument("--waiver-report", action="store_true",
                    help="print the tree-wide waiver-hygiene report "
                         "(per-rule counts for ndslint AND ndsraces, "
                         "stale waivers flagged)")
    args = ap.parse_args(argv)
    repo = pathlib.Path(__file__).resolve().parent.parent
    if args.waiver_report:
        # the report spans both gates; the shared implementation lives
        # with the younger tool (lazy import breaks the import cycle)
        import ndsraces
        return ndsraces.waiver_report(repo, verbose=args.verbose)
    return run(repo, verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
