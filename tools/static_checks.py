"""One-shot static gate: headers + trace schema + ndslint + plan verify.

The single entrypoint tier-1 runs (tests/test_static_analysis.py) and
the one to run locally before pushing:

  1. check_headers      every module opens with a design-intent docstring
  2. check_trace_schema the obs tracer's Chrome-trace JSONL export
                        round-trips through the schema validator (a
                        real trace is generated in-process — no
                        accelerator, no jax import)
  3. ndslint            hazard-class lint over nds_tpu/ + tools/
                        (rules + waiver semantics:
                        nds_tpu/analysis/lint_rules.py)
  3b. ndsraces          concurrency audit over nds_tpu/ (guard
                        inference, static lock-order graph,
                        signal-handler safety, thread-shared mutation;
                        rules NDSR201-204:
                        nds_tpu/analysis/concurrency.py) — zero
                        unwaived findings, stale waivers fail
  3c. ndsjit            recompile & transfer hazard audit over
                        nds_tpu/ (traced-value leaks into Python
                        control flow, fingerprint-blind closure
                        captures, implicit device->host syncs in
                        dispatch code, weak-typed literals at jit
                        boundaries; rules NDSJ301-304:
                        nds_tpu/analysis/jit_hazards.py) — zero
                        unwaived findings, stale waivers fail
  4. ndsverify          plan + verify all 103 NDS and 22 NDS-H
                        statements on CPU (invariants:
                        nds_tpu/analysis/plan_verify.py), each with a
                        placement assigned by the scheduler cost model
                        (engine/scheduler.py) — no accelerator
  5. chaos              3-query NDS power stream on CPU under a fixed
                        fault schedule: one transient injection must
                        retry and complete, one deterministic must
                        fail fast; plus the resume-journal round-trip,
                        a FULL-LADDER walk under injected device OOM
                        (every query completes at the floor with rows
                        identical to a clean CPU run), a virtual-mesh
                        CONSENSUS demotion (sharded OOM reschedules
                        through the vote, the stream start demotes,
                        no deadlock), a SUPERVISED 4-stream throughput
                        round with an injected hang (watchdog catches
                        it within 2x stall_s, stream restarts once,
                        round completes degraded), and an injected
                        io.read byte-flip (digest verification fails
                        the load fast with CorruptArtifact, zero
                        retries) (tools/chaos_check.py)
  6. ndsreport          run-analysis self-check over the committed
                        fixture run-dirs (tests/fixtures/run_*):
                        attribution sums to wall-clock, the regression
                        pair fails the gate, the identity diff passes,
                        and every fixture BenchReport validates against
                        the summary schema (tools/ndsreport.py,
                        nds_tpu/obs/analyze.py)
  7. ndsperf            operator-kernel microbenchmark smoke
                        (tools/ndsperf.py --smoke): every lane runs
                        BOTH the legacy sort-based path and the
                        tensorized kernel (engine/kernels.py) at tiny
                        sizes and cross-checks their results — tier-1
                        proves both kernel paths stay runnable; the
                        speed acceptance runs on real accelerators
  8. fleet              2-process NDS-H power run on a virtual mesh
                        with 30s artificial clock skew and an induced
                        stall: per-rank trace shards merge into ONE
                        clock-aligned timeline with straggler
                        attribution, every rank's watchdog dumps a
                        schema-valid flight-r<rank>.json plus an
                        on-demand XLA capture pointed at from the
                        stall report, and a profile-triggered query's
                        BenchReport carries a nonzero profile block
                        (tools/fleet_check.py; obs/fleet.py +
                        obs/profile.py)
  9. soak               chaos soak smoke (tools/soak_check.py): a
                        real NDS power-run subprocess is SIGTERM'd
                        mid-query (drain deadline -> journaled
                        not-done -> exit 75) and kill -9'd mid-query,
                        each then resumed with --resume; the gate
                        asserts every statement completed exactly
                        once, result digests are byte-identical to an
                        uninterrupted run, the merged phase report +
                        ndsreport bill merged incarnations once, and
                        the torn-state path never fired
 10. compress           columnar compression gate
                        (tools/compress_check.py): a 3-query NDS-H
                        power stream runs on the device placement
                        encoded (columnar.encode=auto) and raw, rows
                        must be IDENTICAL with >=2x measured
                        bytes_scanned drop on at least one query and
                        a compression_ratio on every encoded summary;
                        plus the table_cache manifest round-trip of
                        per-column encoding specs and its mode-change
                        invalidation (nds_tpu/columnar/; README
                        "Compressed columnar store")
 10c. cost             compiler-cost-ledger gate (tools/cost_check.py):
                        a 3-query NDS-H power stream against a fresh
                        plan-cache dir runs cold then warm — every
                        query's BenchReport cost block carries
                        flops > 0 on the cold compile AND on the warm
                        cache hit (zero compiles: the cost dicts ride
                        the AOT manifest), categories+residual ==
                        wall-clock stays intact, the no-stats CPU
                        backend grows no telemetry block, and
                        ndsreport bank mints a provenance-stamped
                        record yet refuses (exit 4) a stale-marked dir
 10b. pipeline          pipelined-execution gate
                        (tools/pipeline_check.py): a 3-query NDS-H
                        power stream FORCED onto the chunked placement
                        (8+ chunks per streamed table) runs serial vs
                        prefetch depth 2 (engine/pipeline_io.py) —
                        rows byte-identical, identical compile counts
                        (the pipeline must not perturb chunkscan
                        fingerprints), measured prefetch_hidden_s > 0,
                        wall-clock no worse; the prefetch run's
                        attribution keeps categories+residual ==
                        wall-clock with the new prefetch_wait
                        category; and an engine.prefetch.boundary=on
                        run (query N+1 dispatched while N's result is
                        in flight) stays byte-identical with
                        schema-valid summaries + a complete journal
 11. serve              query-server smoke (tools/serve_check.py): a
                        warmed QueryServer (nds_tpu/serve/) handles a
                        mixed NDS+NDS-H literal-variant load at >=4
                        concurrent in-flight requests with ZERO
                        compiles and zero plan-cache misses
                        (parameterized fingerprints: variants share
                        one cache entry), responses digest-identical
                        to a sequential oracle, tenant-labeled
                        OpenMetrics + schema-clean per-request
                        summaries + per-tenant p50/p99 via ndsreport
                        analyze, an overload burst sheds
                        (server_shed_total > 0) without a single
                        error, and the TCP JSON-lines front answers
 10d. maint             crash-safe writable-warehouse gate
                        (tools/maint_check.py): a real full-bench run
                        (load -> power -> throughput -> maintenance ->
                        validate -> metric, SF0.01, 3-query streams)
                        is SIGKILLed mid-maintenance while a fault
                        injection wedges LF_WS inside dml.apply, then
                        resumed — the maintenance commit journal must
                        show ZERO double-applied functions (committed
                        ones keep starts==[0], the victim re-runs
                        exactly once), the validate phase must match a
                        CPU oracle on the maintained warehouse, the
                        metric folds both Tdm terms, every mutated
                        table keeps its BASELINE parts + _v*/ delta
                        segments (base never rewritten) with device
                        compression_ratio > 1, rollback restores the
                        pre-maintenance power digests byte-identically,
                        and DML invalidation is table-scoped (an
                        unrelated query re-runs with zero compiles)
 11b. serve-fleet       replicated fleet gate
                        (tools/fleet_serve_check.py): 3 real replica
                        PROCESSES (one started after warmup, warm
                        from the shared AOT store) behind the
                        FleetRouter take a mixed literal-variant load
                        at >=40 concurrency while one replica is
                        SIGKILLed and another SIGTERMed mid-load
                        (drain -> exit 75 -> warm resume ->
                        re-admission); every request completes,
                        traffic redistributes, the request journal
                        proves zero lost / zero double-answered,
                        every response is digest-identical to a
                        sequential single-engine oracle, every
                        post-warmup incarnation reports ZERO compiles
                        / cache misses, and ndsreport analyze derives
                        the per-replica latency rollup
 12. locksan            runtime lock-order sanitizer verdict
                        (nds_tpu/analysis/locksan.py): a SEEDED
                        inversion + re-entrant acquire on a private
                        graph must be caught (the detector provably
                        fires), the chaos/soak/serve/fleet workloads
                        above — which all ran with NDS_TPU_LOCKSAN=1 —
                        must have witnessed ZERO inversions in this
                        process, and every child-process report swept
                        from NDS_TPU_LOCKSAN_REPORT must be
                        inversion-free too
 13. jitsan             runtime jit sanitizer verdict
                        (nds_tpu/analysis/jitsan.py): a SEEDED
                        post-warmup compile + hidden .item() on a
                        private sanitizer must be caught, every
                        measurement window armed by the cost/serve
                        sections above — which ran with
                        NDS_TPU_JITSAN=1 — must be free of post-warmup
                        compiles and undeclared implicit transfers
                        while crossing at least one guarded dispatch
                        site, and every child report swept from
                        NDS_TPU_JITSAN_REPORT must be clean too

Exit 0 only when every section passes; each section prints its own
verdict line so CI logs show exactly which gate broke.
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# before ANY nds_tpu import: module-level locks (obs/trace,
# resilience/watchdog, the metrics registry) are created at import
# time, and they must be sanitizer-wrapped for the locksan section's
# inversion-free verdict over the chaos/soak/serve workloads to mean
# anything. FORCED, not setdefault: an ambient NDS_TPU_LOCKSAN=0 (the
# pytest debugging opt-out) would make section 12's verdict vacuous.
os.environ["NDS_TPU_LOCKSAN"] = "1"
# same reasoning for the jit sanitizer: cost_check's warm stream and
# serve_check's post-warmup phases arm measurement windows, and the
# jitsan section's verdict over them is only meaningful if the env was
# on for the whole process
os.environ["NDS_TPU_JITSAN"] = "1"

import chaos_check  # noqa: E402
import check_headers  # noqa: E402
import check_trace_schema  # noqa: E402
import compress_check  # noqa: E402
import cost_check  # noqa: E402
import fleet_check  # noqa: E402
import fleet_serve_check  # noqa: E402
import maint_check  # noqa: E402
import ndslint  # noqa: E402
import ndsperf  # noqa: E402
import ndsjit  # noqa: E402
import ndsraces  # noqa: E402
import ndsreport  # noqa: E402
import ndsverify  # noqa: E402
import pipeline_check  # noqa: E402
import serve_check  # noqa: E402
import soak_check  # noqa: E402


def run_trace_schema_check() -> int:
    """Exercise the tracer end-to-end: emit a real span tree to a JSONL
    file and validate it against the documented event schema."""
    from nds_tpu.obs.trace import Tracer, export_chrome
    tracer = Tracer(enabled=True)
    with tracer.span("static_checks.trace_selftest", gate="tier-1"):
        with tracer.span("static_checks.child", n=1):
            pass
    roots = getattr(tracer, "last_roots", None)
    if not roots:  # tracer API drift: fail loudly, not silently
        print("FAIL: tracer produced no root span")
        return 1
    root = roots[-1]
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        path = f.name
    try:
        export_chrome(root, path)
        errors = check_trace_schema.validate_file(path)
        for e in errors:
            print(e)
        print(f"{'FAIL' if errors else 'OK'}: {len(errors)} schema "
              f"error(s) in generated trace")
        return 1 if errors else 0
    finally:
        os.unlink(path)


def run_ndsreport_check() -> int:
    """Section 6: analyze + diff over the committed fixtures, plus the
    BenchReport summary-schema gate over every fixture report."""
    import glob
    import pathlib
    repo = pathlib.Path(__file__).resolve().parent.parent
    rc = ndsreport.self_check(str(repo))
    errors = []
    from nds_tpu.obs import analyze
    for path in sorted(glob.glob(
            str(repo / "tests" / "fixtures" / "run_*" / "*.json"))):
        # a local `ndsreport analyze tests/fixtures/run_a` drops its
        # analysis.json into the run dir — an artifact, not a fixture
        if not analyze.is_report_basename(os.path.basename(path)):
            continue
        errors.extend(check_trace_schema.validate_summary_file(path))
    for e in errors:
        print(e)
    if errors:
        print(f"FAIL: {len(errors)} summary schema error(s) in "
              f"fixtures")
    return 1 if (rc or errors) else 0


def run_locksan_check() -> int:
    """Section 12: the runtime sanitizer verdict. Three parts:
    (1) a seeded AB/BA inversion plus a re-entrant acquire on a
    PRIVATE graph must be caught — the detector provably fires;
    (2) this process, which ran the chaos/compress/serve workloads
    with every engine lock wrapped, must hold zero inversions;
    (3) child processes (fleet/soak subprocess runs) wrote
    locksan-<pid>.json reports into NDS_TPU_LOCKSAN_REPORT at exit —
    sweep them, all must be inversion-free."""
    import glob
    import json
    from nds_tpu.analysis import locksan
    if not locksan.enabled():
        # belt for the forced env above: an unsanitized run has no
        # inversion-free claim to make, and silence would fake one
        print(f"FAIL: {locksan.ENV} is off — the workloads above ran "
              f"unsanitized, so this verdict would be vacuous")
        return 1
    if not locksan.selftest():
        print("FAIL: locksan missed the seeded inversion")
        return 1
    inproc = locksan.inversion_count()
    child_inv = 0
    reports = 0
    report_dir = os.environ.get(locksan.REPORT_ENV)
    if report_dir:
        for path in sorted(glob.glob(
                os.path.join(report_dir, "locksan-*.json"))):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            reports += 1
            for inv in doc.get("inversions", []):
                child_inv += 1
                print(f"  child inversion ({os.path.basename(path)}): "
                      f"{' -> '.join(inv.get('cycle', []))}")
    bad = inproc + child_inv
    print(f"{'FAIL' if bad else 'OK'}: seeded inversion caught; "
          f"{inproc} in-process + {child_inv} child inversion(s) "
          f"across {reports} child report(s)")
    return 1 if bad else 0


def run_jitsan_check() -> int:
    """Section 13: the jit sanitizer verdict. Three parts:
    (1) a seeded post-warmup compile + hidden ``.item()`` on a private
    sanitizer must be caught — the detector provably fires;
    (2) every measurement window closed in this process (cost_check's
    warm stream, serve_check's post-warmup phases, both armed because
    NDS_TPU_JITSAN is forced above) must be violation-free AND at
    least one must have crossed a guarded dispatch site — a clean
    verdict over zero dispatches proves only that the guard is
    unwired;
    (3) child-process reports swept from NDS_TPU_JITSAN_REPORT must be
    violation-free too."""
    import glob
    import json
    from nds_tpu.analysis import jitsan
    if not jitsan.enabled():
        print(f"FAIL: {jitsan.ENV} is off — the cost/serve windows "
              f"above ran unsanitized, so this verdict would be "
              f"vacuous")
        return 1
    if not jitsan.selftest():
        print("FAIL: jitsan missed the seeded compile/transfer")
        return 1
    wins = jitsan.windows()
    inproc = jitsan.violation_count()
    dispatches = sum(w.get("dispatches", 0) for w in wins)
    if not wins or dispatches == 0:
        print(f"FAIL: no armed window crossed a dispatch site "
              f"({len(wins)} window(s)) — the cost/serve sections "
              f"above did not measure anything")
        return 1
    child_bad = 0
    reports = 0
    report_dir = os.environ.get(jitsan.REPORT_ENV)
    if report_dir:
        for path in sorted(glob.glob(
                os.path.join(report_dir, "jitsan-*.json"))):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            reports += 1
            for w in doc.get("windows", []):
                for c in w.get("compiles", []):
                    child_bad += 1
                    print(f"  child compile "
                          f"({os.path.basename(path)}): "
                          f"{w.get('label')}: {c.get('kind')}")
                for t in w.get("undeclared_transfers", []):
                    child_bad += 1
                    print(f"  child transfer "
                          f"({os.path.basename(path)}): "
                          f"{w.get('label')}: {t.get('what')}")
    bad = inproc + child_bad
    print(f"{'FAIL' if bad else 'OK'}: seeded compile+transfer "
          f"caught; {inproc} in-process + {child_bad} child "
          f"violation(s) across {len(wins)} window(s) "
          f"({dispatches} guarded dispatches) and {reports} child "
          f"report(s)")
    return 1 if bad else 0


def main() -> int:
    import pathlib
    repo = pathlib.Path(__file__).resolve().parent.parent
    # child runs (fleet/soak/serve subprocesses) inherit this dir and
    # write their sanitizer reports into it at exit; section 12 sweeps
    # it (children killed with -9 or os._exit leave none — best effort)
    os.environ.setdefault(
        "NDS_TPU_LOCKSAN_REPORT",
        tempfile.mkdtemp(prefix="nds_tpu_locksan_"))
    os.environ.setdefault(
        "NDS_TPU_JITSAN_REPORT",
        tempfile.mkdtemp(prefix="nds_tpu_jitsan_"))
    sections = [
        ("headers", check_headers.main),
        ("trace-schema", run_trace_schema_check),
        ("ndslint", lambda: ndslint.run(repo)),
        ("ndsraces", lambda: ndsraces.run(repo)),
        ("ndsjit", lambda: ndsjit.run(repo)),
        ("ndsverify", lambda: ndsverify.main([])),
        ("chaos", chaos_check.main),
        ("ndsreport", run_ndsreport_check),
        ("ndsperf", lambda: ndsperf.main(["--smoke"])),
        ("fleet", fleet_check.main),
        ("soak", lambda: soak_check.main([])),
        ("compress", lambda: compress_check.main([])),
        ("pipeline", lambda: pipeline_check.main([])),
        ("cost", lambda: cost_check.main([])),
        ("maint", lambda: maint_check.main([])),
        ("serve", lambda: serve_check.main([])),
        ("serve-fleet", lambda: fleet_serve_check.main([])),
        ("locksan", run_locksan_check),
        ("jitsan", run_jitsan_check),
    ]
    failed = []
    for name, fn in sections:
        print(f"== {name} ==")
        if fn() != 0:
            failed.append(name)
    if failed:
        print(f"STATIC CHECKS FAILED: {', '.join(failed)}")
        return 1
    print(f"STATIC CHECKS OK: {len(sections)} section(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
