"""CLI for the run-analysis layer (nds_tpu/obs/analyze.py).

Two verbs over run directories (a run dir = the folder a power or
throughput run wrote its per-query BenchReport JSONs into, plus any
Chrome-trace ``*.jsonl``):

  python tools/ndsreport.py analyze RUN_DIR [--out DIR] [--top N]
      Print the per-query time-attribution table (categories +
      residual sum to wall-clock by construction) and write
      ``analysis.json`` + self-contained ``report.html`` to --out
      (default: RUN_DIR).

  python tools/ndsreport.py diff BASE_DIR CUR_DIR [--gate pct=10,abs_ms=50]
      Query-by-query steady-state comparison with a noise-aware
      regression gate. Exit 0 when the gate passes, 1 on regression /
      removed query / newly-failed query — so CI and bench rounds can
      gate on it directly.

``self_check()`` is the tier-1 entry (tools/static_checks.py section
6): analyze + diff over the committed fixture run-dirs under
``tests/fixtures/`` — the attribution-sum invariant and both gate
verdicts are asserted against known-good data on every run.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from nds_tpu.obs import analyze  # noqa: E402


def cmd_analyze(args) -> int:
    a = analyze.analyze_run(args.run_dir)
    print(analyze.format_attribution(a, top=args.top))
    for name, h in sorted(a["metrics"]["histograms"].items()):
        qs = "".join(f" {k}={h[k]:g}" for k in ("p50", "p95", "p99")
                     if h.get(k) is not None)
        print(f"hist {name}: count={h['count']:g} "
              f"sum={h['sum']:g}{qs}")
    for tenant, q in sorted((a.get("tenants") or {}).items()):
        # serving run dirs (nds_tpu/serve/): per-tenant latency line
        print(f"tenant {tenant}: requests={q['requests']} "
              f"p50={q.get('p50_ms')}ms p95={q.get('p95_ms')}ms "
              f"p99={q.get('p99_ms')}ms")
    if a.get("stale_device_times"):
        print(f"WARNING: {len(a['stale_device_times'])} summar"
              f"{'y' if len(a['stale_device_times']) == 1 else 'ies'} "
              f"carry banked/stale device times — not fresh "
              f"measurements (ndsreport diff refuses to gate on them)")
    out_dir = args.out or args.run_dir
    paths = analyze.write_outputs(a, out_dir)
    print(f"wrote {paths['analysis']} and {paths['report']}")
    return 1 if a["failed"] and args.strict else 0


def cmd_diff(args) -> int:
    gate = analyze.parse_gate(args.gate)
    # the gate only compares BenchReport-derived rows; parsing two
    # full Chrome traces would double its wall-clock for nothing —
    # load the current run's trace only when writing the HTML report
    base = analyze.analyze_run(args.base_dir, with_trace=False)
    cur = analyze.analyze_run(args.cur_dir,
                              with_trace=bool(args.out))
    d = analyze.diff_runs(base, cur, **gate)
    print(analyze.format_diff(d))
    if args.out:
        paths = analyze.write_outputs(cur, args.out, diff=d)
        print(f"wrote {paths['analysis']} and {paths['report']}")
    return 0 if d["passed"] else 1


def self_check(repo_root: str | None = None) -> int:
    """Tier-1 gate over the committed fixtures: the attribution
    invariant holds, the regression pair fails the gate for the right
    reasons, and the identity diff passes."""
    repo = repo_root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    run_a = os.path.join(repo, "tests", "fixtures", "run_a")
    run_b = os.path.join(repo, "tests", "fixtures", "run_b")
    errors = []
    try:
        a = analyze.analyze_run(run_a)
        b = analyze.analyze_run(run_b)
    except Exception as exc:  # noqa: BLE001 - report, don't crash CI
        print(f"FAIL: fixture analysis raised {type(exc).__name__}: "
              f"{exc}")
        return 1
    for run in (a, b):
        for row in run["queries"]:
            total = (sum(row["categories"].values())
                     + row["residual_ms"])
            if abs(total - row["wall_ms"]) > 1e-6:
                errors.append(
                    f"{row['query']}: categories+residual "
                    f"{total:.3f} != wall {row['wall_ms']:.3f}")
    html = analyze.render_html(a)
    if "</html>" not in html or "attribution" not in html:
        errors.append("render_html produced no report body")
    d = analyze.diff_runs(a, b, pct=10.0, abs_ms=50.0)
    if d["passed"]:
        errors.append("regression fixture pair PASSED the gate")
    if not any(e["query"] == "query1" for e in d["regressions"]):
        errors.append("query1 regression not detected")
    if any(e["query"] == "query3" for e in
           d["regressions"] + d["improvements"]):
        errors.append("query3 noise misclassified as signal")
    ident = analyze.diff_runs(a, a, pct=10.0, abs_ms=50.0)
    if not ident["passed"]:
        errors.append("identity diff failed the gate")
    for e in errors:
        print(f"FAIL: {e}")
    print(f"{'FAIL' if errors else 'OK'}: ndsreport self-check, "
          f"{len(errors)} error(s)")
    return 1 if errors else 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="analyze/diff benchmark run directories")
    sub = p.add_subparsers(dest="cmd", required=True)
    pa = sub.add_parser("analyze", help="attribution table + report")
    pa.add_argument("run_dir")
    pa.add_argument("--out", help="artifact dir (default: run_dir)")
    pa.add_argument("--top", type=int, default=None,
                    help="only the N slowest queries in the table")
    pa.add_argument("--strict", action="store_true",
                    help="exit 1 when any query failed")
    pd = sub.add_parser("diff", help="cross-run regression gate")
    pd.add_argument("base_dir")
    pd.add_argument("cur_dir")
    pd.add_argument("--gate", default=None,
                    help="thresholds, e.g. pct=10,abs_ms=50")
    pd.add_argument("--out",
                    help="also write analysis.json/report.html with "
                         "the diff embedded")
    sub.add_parser("self-check", help="fixture-based CI self-check")
    args = p.parse_args(argv)
    if args.cmd == "analyze":
        return cmd_analyze(args)
    if args.cmd == "diff":
        return cmd_diff(args)
    return self_check()


if __name__ == "__main__":
    sys.exit(main())
