"""CLI for the run-analysis layer (nds_tpu/obs/analyze.py).

Two verbs over run directories (a run dir = the folder a power or
throughput run wrote its per-query BenchReport JSONs into, plus any
Chrome-trace ``*.jsonl``):

  python tools/ndsreport.py analyze RUN_DIR [--out DIR] [--top N]
      Print the per-query time-attribution table (categories +
      residual sum to wall-clock by construction) and write
      ``analysis.json`` + self-contained ``report.html`` to --out
      (default: RUN_DIR).

  python tools/ndsreport.py diff BASE_DIR CUR_DIR [--gate pct=10,abs_ms=50,cost_pct=25]
      Query-by-query steady-state comparison with a noise-aware
      regression gate (plus the COST-DRIFT gate over compiler
      flops/bytes). Exit 0 when the gate passes, 1 on regression /
      removed query / newly-failed query — so CI and bench rounds can
      gate on it directly.

  python tools/ndsreport.py bank RUN_DIR [--out PATH]
      Mint a BENCH-record-shaped JSON mechanically from a run dir,
      stamped with provenance (platform, engine version, config
      digest, code_epoch, compiler cost totals) — BENCH_r06 is one
      command, not hand-rolled numbers (the r04/r05 rot class).
      REFUSES loudly when any summary carries ``stale_device_times``:
      exit 4 (the bench.py EXIT_STALE_METRIC contract — a banked
      number from banked inputs is exactly the rot this exists to
      stop); exit 5 when the dir has no completed measurements.

``self_check()`` is the tier-1 entry (tools/static_checks.py section
6): analyze + diff over the committed fixture run-dirs under
``tests/fixtures/`` — the attribution-sum invariant and both gate
verdicts are asserted against known-good data on every run.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from nds_tpu.obs import analyze  # noqa: E402

# bank refusal exit codes — the bench.py contract (EXIT_STALE_METRIC /
# EXIT_NO_METRIC): a banked number must be a LOUD failure when its
# inputs were stale or absent, never a quietly-zero record
EXIT_STALE_BANK = 4
EXIT_NO_METRIC = 5

# engineConf keys that describe the live process, not the bench
# configuration — excluded from the banked config digest so the same
# config banks the same digest across hosts/device counts
_VOLATILE_CONF_KEYS = ("backend", "device_count", "devices")


def bank_record(run_dir: str) -> "tuple[dict | None, str]":
    """(record, error) for a run dir — record is None exactly when the
    dir must not bank (the error says why). Everything in the record
    is derived mechanically from the summaries ALREADY on disk: no
    live jax calls (the utils/report.py dead-tunnel rule — banking a
    finished run must work from any host)."""
    import time

    from nds_tpu.cache.fingerprint import code_epoch
    from nds_tpu.resilience.journal import config_digest
    try:
        a = analyze.analyze_run(run_dir, with_trace=False)
    except ValueError as exc:
        return None, str(exc)
    if a.get("stale_device_times"):
        names = ", ".join(a["stale_device_times"])
        return None, (f"run dir carries banked/stale device times "
                      f"({names}) — refusing to mint a BENCH record "
                      f"from numbers nobody measured this run")
    rows = [r for r in a["queries"] if r["status"] == "Completed"]
    if not rows:
        return None, "no completed query summaries to bank"
    summaries = analyze.load_summaries(run_dir)
    env = (summaries[0].get("env") or {}) if summaries else {}
    conf = {k: v for k, v in (env.get("engineConf") or {}).items()
            if k not in _VOLATILE_CONF_KEYS}
    # platform: the cost blocks' device-kind stamp when the run
    # carried the cost ledger, else the recorded backend
    platforms = sorted({r["cost"]["platform"] for r in rows
                       if isinstance(r.get("cost"), dict)
                       and r["cost"].get("platform")})
    provenance = {
        "platform": (platforms[0] if len(platforms) == 1
                     else (env.get("engineConf") or {}).get(
                         "backend", "unknown")),
        "engine_version": env.get("engineVersion") or "unknown",
        "config_digest": config_digest(conf),
        "code_epoch": code_epoch(),
        "banked_at": int(time.time()),
        "run_dir": a["run_dir"],
    }
    totals: dict = {}
    programs = 0
    with_cost = 0
    for r in rows:
        cost = r.get("cost")
        if not isinstance(cost, dict):
            continue
        with_cost += 1
        for k in ("flops", "bytes_accessed", "transcendentals"):
            v = cost.get(k)
            if isinstance(v, (int, float)) and v > 0:
                totals[k] = totals.get(k, 0.0) + float(v)
        programs += sum(int(n) for n in
                        (cost.get("programs") or {}).values())
    record = {
        "metric": "power_total",
        "value": round(sum(r["wall_ms"] for r in rows) / 1000.0, 4),
        "unit": "s",
        "queries_completed": len(rows),
        "queries_total": len(a["queries"]),
        "per_query": {r["query"]: round(r["wall_ms"] / 1000.0, 4)
                      for r in rows},
        "provenance": provenance,
    }
    if with_cost:
        record["cost_totals"] = {**{k: totals[k] for k in sorted(totals)},
                                 "programs": programs,
                                 "queries_with_cost": with_cost}
    if a.get("failed"):
        record["failed"] = list(a["failed"])
    return record, ""


def cmd_bank(args) -> int:
    import json
    record, err = bank_record(args.run_dir)
    if record is None:
        stale = "stale" in err
        print(f"BANK REFUSED: {err}")
        return EXIT_STALE_BANK if stale else EXIT_NO_METRIC
    out = args.out or os.path.join(args.run_dir, "bench_record.json")
    from nds_tpu.io.integrity import write_json_atomic
    write_json_atomic(out, record)
    print(json.dumps(record))
    print(f"wrote {out}")
    return 0


def cmd_analyze(args) -> int:
    a = analyze.analyze_run(args.run_dir)
    print(analyze.format_attribution(a, top=args.top))
    for name, h in sorted(a["metrics"]["histograms"].items()):
        qs = "".join(f" {k}={h[k]:g}" for k in ("p50", "p95", "p99")
                     if h.get(k) is not None)
        print(f"hist {name}: count={h['count']:g} "
              f"sum={h['sum']:g}{qs}")
    for tenant, q in sorted((a.get("tenants") or {}).items()):
        # serving run dirs (nds_tpu/serve/): per-tenant latency line
        print(f"tenant {tenant}: requests={q['requests']} "
              f"p50={q.get('p50_ms')}ms p95={q.get('p95_ms')}ms "
              f"p99={q.get('p99_ms')}ms")
    for rep, q in sorted((a.get("replicas") or {}).items()):
        # fleet run dirs: per-replica latency line; OUTLIER means the
        # replica's p99 diverges >2x from the fleet median — a sick
        # member, not a workload property
        flag = "  OUTLIER(p99>2x fleet median)" if q.get(
            "outlier") else ""
        print(f"replica {rep}: requests={q['requests']} "
              f"p50={q.get('p50_ms')}ms p95={q.get('p95_ms')}ms "
              f"p99={q.get('p99_ms')}ms{flag}")
    if a.get("stale_device_times"):
        print(f"WARNING: {len(a['stale_device_times'])} summar"
              f"{'y' if len(a['stale_device_times']) == 1 else 'ies'} "
              f"carry banked/stale device times — not fresh "
              f"measurements (ndsreport diff refuses to gate on them)")
    out_dir = args.out or args.run_dir
    paths = analyze.write_outputs(a, out_dir)
    print(f"wrote {paths['analysis']} and {paths['report']}")
    return 1 if a["failed"] and args.strict else 0


def cmd_diff(args) -> int:
    gate = analyze.parse_gate(args.gate)
    # the gate only compares BenchReport-derived rows; parsing two
    # full Chrome traces would double its wall-clock for nothing —
    # load the current run's trace only when writing the HTML report
    base = analyze.analyze_run(args.base_dir, with_trace=False)
    cur = analyze.analyze_run(args.cur_dir,
                              with_trace=bool(args.out))
    d = analyze.diff_runs(base, cur, **gate)
    print(analyze.format_diff(d))
    if args.out:
        paths = analyze.write_outputs(cur, args.out, diff=d)
        print(f"wrote {paths['analysis']} and {paths['report']}")
    return 0 if d["passed"] else 1


def self_check(repo_root: str | None = None) -> int:
    """Tier-1 gate over the committed fixtures: the attribution
    invariant holds, the regression pair fails the gate for the right
    reasons, and the identity diff passes."""
    repo = repo_root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    run_a = os.path.join(repo, "tests", "fixtures", "run_a")
    run_b = os.path.join(repo, "tests", "fixtures", "run_b")
    errors = []
    try:
        a = analyze.analyze_run(run_a)
        b = analyze.analyze_run(run_b)
    except Exception as exc:  # noqa: BLE001 - report, don't crash CI
        print(f"FAIL: fixture analysis raised {type(exc).__name__}: "
              f"{exc}")
        return 1
    for run in (a, b):
        for row in run["queries"]:
            total = (sum(row["categories"].values())
                     + row["residual_ms"])
            if abs(total - row["wall_ms"]) > 1e-6:
                errors.append(
                    f"{row['query']}: categories+residual "
                    f"{total:.3f} != wall {row['wall_ms']:.3f}")
    html = analyze.render_html(a)
    if "</html>" not in html or "attribution" not in html:
        errors.append("render_html produced no report body")
    d = analyze.diff_runs(a, b, pct=10.0, abs_ms=50.0)
    if d["passed"]:
        errors.append("regression fixture pair PASSED the gate")
    if not any(e["query"] == "query1" for e in d["regressions"]):
        errors.append("query1 regression not detected")
    if any(e["query"] == "query3" for e in
           d["regressions"] + d["improvements"]):
        errors.append("query3 noise misclassified as signal")
    ident = analyze.diff_runs(a, a, pct=10.0, abs_ms=50.0)
    if not ident["passed"]:
        errors.append("identity diff failed the gate")
    for e in errors:
        print(f"FAIL: {e}")
    print(f"{'FAIL' if errors else 'OK'}: ndsreport self-check, "
          f"{len(errors)} error(s)")
    return 1 if errors else 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="analyze/diff benchmark run directories")
    sub = p.add_subparsers(dest="cmd", required=True)
    pa = sub.add_parser("analyze", help="attribution table + report")
    pa.add_argument("run_dir")
    pa.add_argument("--out", help="artifact dir (default: run_dir)")
    pa.add_argument("--top", type=int, default=None,
                    help="only the N slowest queries in the table")
    pa.add_argument("--strict", action="store_true",
                    help="exit 1 when any query failed")
    pd = sub.add_parser("diff", help="cross-run regression gate")
    pd.add_argument("base_dir")
    pd.add_argument("cur_dir")
    pd.add_argument("--gate", default=None,
                    help="thresholds, e.g. pct=10,abs_ms=50,"
                         "cost_pct=25")
    pd.add_argument("--out",
                    help="also write analysis.json/report.html with "
                         "the diff embedded")
    pb = sub.add_parser(
        "bank", help="mint a provenance-stamped BENCH record")
    pb.add_argument("run_dir")
    pb.add_argument("--out",
                    help="record path (default: "
                         "RUN_DIR/bench_record.json)")
    sub.add_parser("self-check", help="fixture-based CI self-check")
    args = p.parse_args(argv)
    if args.cmd == "analyze":
        return cmd_analyze(args)
    if args.cmd == "diff":
        return cmd_diff(args)
    if args.cmd == "bank":
        return cmd_bank(args)
    return self_check()


if __name__ == "__main__":
    sys.exit(main())
