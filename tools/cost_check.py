"""Compiler-cost-ledger gate: every program billed, cold and warm.

tier-1 (via tools/static_checks.py) proves the cost ledger
(nds_tpu/obs/costs.py; README "Cost ledger & telemetry") end-to-end on
the CPU backend with a 3-query NDS-H power stream (q1/q3/q6) against a
fresh AOT plan-cache directory:

1. **cold compile** — every query's BenchReport carries a ``cost``
   block with ``flops > 0`` and a non-empty ``programs`` census, the
   run actually compiled (``compiles_total > 0``), and the plan cache
   recorded misses — the dispatch-site hooks fire on freshly-built
   executables.
2. **warm cache hit** — the SAME stream against the SAME cache dir:
   zero compiles (every program loads from the store), plan-cache hits
   recorded, and STILL ``flops > 0`` on every query — the cost dicts
   ride the cache payload/manifest (``cache/aot.py`` persists them),
   so warm runs bill compiler-truth numbers they never recomputed.
3. **attribution invariant** — categories + residual == wall-clock per
   query over the warm run (the new cost/telemetry columns must not
   perturb ndsreport's accounting), and on this no-stats backend the
   summaries carry NO ``telemetry`` block (the sampler's graceful
   no-op keeps pre-telemetry shapes byte-identical).
4. **bank refusal** — ``ndsreport bank`` mints a provenance-stamped
   record with positive ``cost_totals`` from the warm dir, and REFUSES
   (exit 4) a copy whose summary is marked ``stale_device_times``.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCALE = 0.01
TEMPLATES = (1, 3, 6)


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _write_stream(path: str) -> None:
    from nds_tpu.nds_h import streams as hstreams
    parts = [f"-- Template file: {qn}\n\n"
             f"{hstreams.render_query(qn, None, stream=0)}\n"
             for qn in TEMPLATES]
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write("\n".join(parts))


def _summaries(jsons: str) -> dict:
    from nds_tpu.obs import analyze
    out = {}
    for name in os.listdir(jsons):
        if not analyze.is_report_basename(name):
            continue
        with open(os.path.join(jsons, name)) as f:
            s = json.load(f)
        if isinstance(s, dict) and "query" in s and "queryStatus" in s:
            out[s["query"]] = s
    return out


def _run_stream(workdir: str, raw: str, stream: str, label: str,
                cache_dir: str) -> "dict | None":
    from nds_tpu.nds_h.power import SUITE
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    jsons = os.path.join(workdir, f"json_{label}")
    out = os.path.join(workdir, f"rows_{label}")
    cfg = EngineConfig(overrides={
        "engine.backend": "tpu",  # tensorized engine on local CPU jax
        "cache.dir": cache_dir,
    })
    failures = power_core.run_query_stream(
        SUITE, raw, stream, os.path.join(workdir, f"{label}.csv"),
        config=cfg, input_format="raw", json_summary_folder=jsons,
        output_prefix=out)
    if failures:
        print(f"FAIL: {failures} query failure(s) in the {label} run")
        return None
    return {"summaries": _summaries(jsons), "jsons": jsons}


def _compiles(summaries: dict) -> int:
    total = 0
    for s in summaries.values():
        c = (s.get("metrics") or {}).get("counters", {})
        total += int(c.get("compiles_total", 0)
                     + c.get("recompiles_total", 0))
    return total


def _cache_counts(summaries: dict) -> "tuple[int, int]":
    hits = misses = 0
    for s in summaries.values():
        cache = s.get("cache") or {}
        hits += int(cache.get("hits", 0))
        misses += int(cache.get("misses", 0))
    return hits, misses


def _check_costs(summaries: dict, label: str) -> "str | None":
    """Every query billed compiler flops through a non-empty program
    census, or the reason it didn't."""
    want = {f"query{qn}" for qn in TEMPLATES}
    if set(summaries) != want:
        return f"{label}: summaries for {sorted(summaries)}, not " \
               f"{sorted(want)}"
    for q in sorted(want):
        cost = summaries[q].get("cost")
        if not isinstance(cost, dict):
            return f"{label}: {q} has no cost block"
        if not (isinstance(cost.get("flops"), (int, float))
                and cost["flops"] > 0):
            return f"{label}: {q} cost.flops = {cost.get('flops')!r}"
        progs = cost.get("programs")
        if not isinstance(progs, dict) or not progs:
            return f"{label}: {q} cost.programs = {progs!r}"
    return None


def run_cold_warm(workdir: str) -> "tuple[int, dict | None]":
    from nds_tpu.nds_h import gen_data
    raw = os.path.join(workdir, "raw")
    stream = os.path.join(workdir, "streams", "stream.sql")
    cache_dir = os.path.join(workdir, "plan_cache")
    gen_data.generate_data_local(SCALE, 2, raw, workers=2)
    _write_stream(stream)
    cold = _run_stream(workdir, raw, stream, "cold", cache_dir)
    if cold is None:
        return 1, None
    bad = _check_costs(cold["summaries"], "cold")
    if bad:
        return _fail(bad), None
    cc = _compiles(cold["summaries"])
    if cc <= 0:
        return _fail(f"cold run compiled nothing (compiles={cc}) — "
                     f"this gate proved nothing"), None
    _ch, cm = _cache_counts(cold["summaries"])
    if cm <= 0:
        return _fail("cold run recorded no plan-cache misses — is the "
                     "cache dir wired?"), None
    # the warm stream runs under an armed jitsan window: the summaries
    # below assert compiles == 0 from the ledger's point of view; the
    # sanitizer asserts the same from the compile funnel's, plus that
    # no undeclared implicit transfer hid in the dispatch path. No-op
    # unless NDS_TPU_JITSAN=1 (static_checks forces it).
    from nds_tpu.analysis import jitsan
    jitsan_armed = jitsan.arm("cost_check.warm")
    try:
        warm = _run_stream(workdir, raw, stream, "warm", cache_dir)
    finally:
        verdict = jitsan.disarm()
    if warm is None:
        return 1, None
    if jitsan_armed:
        if verdict["compiles"]:
            return _fail(
                f"jitsan: warm run compiled "
                f"{[c['kind'] for c in verdict['compiles']]} past the "
                f"ledger"), None
        if verdict["undeclared_transfers"]:
            return _fail(
                f"jitsan: warm run hid implicit transfer(s) "
                f"{[t['what'] for t in verdict['undeclared_transfers']]}"
            ), None
        if verdict["dispatches"] == 0:
            return _fail("jitsan: warm window saw zero dispatch "
                         "crossings — guard not wired"), None
        print(f"OK: jitsan warm window clean — 0 compiles, 0 "
              f"undeclared transfers across {verdict['dispatches']} "
              f"guarded dispatches")
    bad = _check_costs(warm["summaries"], "warm")
    if bad:
        return _fail(bad), None
    wc = _compiles(warm["summaries"])
    if wc != 0:
        return _fail(f"warm run compiled {wc} program(s) — cache "
                     f"misses mean the cost blocks above prove "
                     f"nothing about the manifest path"), None
    wh, _wm = _cache_counts(warm["summaries"])
    if wh <= 0:
        return _fail("warm run recorded no plan-cache hits"), None
    print(f"OK: cold/warm — flops billed on all {len(TEMPLATES)} "
          f"queries both ways ({cc} cold compile(s), 0 warm, "
          f"{wh} warm cache hit(s))")
    return 0, warm


def run_attribution(warm: dict) -> int:
    from nds_tpu.obs import analyze
    a = analyze.analyze_run(warm["jsons"], with_trace=False)
    for row in a["queries"]:
        total = sum(row["categories"].values()) + row["residual_ms"]
        if abs(total - row["wall_ms"]) > 1e-6:
            return _fail(f"{row['query']}: categories+residual "
                         f"{total:.3f} != wall {row['wall_ms']:.3f}")
    # CPU has no allocator stats: the sampler must leave no trace
    with_tel = [q for q, s in warm["summaries"].items()
                if "telemetry" in s]
    if with_tel:
        return _fail(f"no-stats backend grew telemetry blocks on "
                     f"{with_tel}")
    print("OK: attribution — invariant holds with cost blocks, "
          "telemetry silent on no-stats backend")
    return 0


def run_bank(workdir: str, warm: dict) -> int:
    import ndsreport
    record, err = ndsreport.bank_record(warm["jsons"])
    if record is None:
        return _fail(f"bank refused a clean run dir: {err}")
    totals = record.get("cost_totals") or {}
    if not totals.get("flops", 0) > 0:
        return _fail(f"banked record has no positive cost_totals "
                     f"({totals!r})")
    stale_dir = os.path.join(workdir, "json_stale")
    shutil.copytree(warm["jsons"], stale_dir)
    name = sorted(n for n in os.listdir(stale_dir)
                  if n.endswith(".json") and "query" in n)[0]
    spath = os.path.join(stale_dir, name)
    with open(spath) as f:
        doc = json.load(f)
    doc["stale_device_times"] = True
    with open(spath, "w") as f:
        json.dump(doc, f)
    rc = ndsreport.main(["bank", stale_dir,
                         "--out", os.path.join(workdir, "nope.json")])
    if rc != ndsreport.EXIT_STALE_BANK:
        return _fail(f"bank exited {rc} (want "
                     f"{ndsreport.EXIT_STALE_BANK}) on a stale-marked "
                     f"dir")
    if os.path.exists(os.path.join(workdir, "nope.json")):
        return _fail("bank wrote a record while refusing")
    print("OK: bank — provenance-stamped record with cost totals; "
          "stale-marked dir refused with exit 4")
    return 0


def main(argv=None) -> int:
    del argv
    with tempfile.TemporaryDirectory(prefix="nds_cost_") as wd:
        print("-- cost_check: cold/warm ledger --")
        rc, warm = run_cold_warm(wd)
        if rc:
            return rc
        print("-- cost_check: attribution --")
        rc = run_attribution(warm)
        if rc:
            return rc
        print("-- cost_check: bank --")
        rc = run_bank(wd, warm)
        if rc:
            return rc
    print("COST CHECK OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
