"""ndsperf: operator microbenchmark for the tensorized kernels.

Benchmarks each hot relational operator OLD path vs NEW kernel
(engine/kernels.py; README "Kernels & roofline") at three sizes, on
whatever backend jax selects, and emits one JSON document:

    python tools/ndsperf.py [--sizes 4096,65536,1048576]
                            [--repeat 5] [--out perf.json] [--smoke]

Benchmark lanes (old -> new):

  join.unique   full-table ``lax.sort`` + searchsorted probe
                (``device_exec._build_lookup``/``_probe``)
                -> dense direct-address lookup (``direct_lookup_join``)
  join.tiny     the same sort+probe against a 32-row build
                -> one-hot MXU matmul probe (``matmul_probe_join``)
  join.mn       flat-sort M:N match-range expansion (the device
                executor's generic inner-join formulation)
                -> radix-partitioned batched sort (``partitioned_mn_join``)
  semi          sort+probe EXISTS -> membership bitmap (``bitmask_semi``)
  agg.minmax    ``jax.ops.segment_min`` scatter over sorted group ids
                -> segmented scan + gather at ends (``seg_reduce_at_ends``)
  sort.width    the NDS112 lint rule's premise, measured: one
                ``lax.sort`` of int64 keys vs the same keys as int32

Encoded-vs-raw lanes (nds_tpu/columnar/; README "Compressed columnar
store") — each measures one encoding's decode fused into its consumer
against the same operator over raw buffers, so the bytes-vs-ALU trade
is visible in isolation:

  enc.bitpack   range filter over raw int64 vs 16-bit fields packed
                into int32 words (gather + shift/mask unpack fused)
  enc.rle       date-range count over a sorted raw int32 column vs
                its run-length form (scatter+cumsum run-id rebuild)
  enc.dictjoin  direct-address dict-code join probing raw int32
                codes vs bit-packed codes unpacked into the gather

Pipeline lanes (engine/pipeline_io.py; README "Pipelined execution") —
an 8-chunk stream whose host half (bitpack encode + device_put) and
device half (fused decode + filter count, compiled once) run the
chunked engine's phase-A shape, serial vs double-buffered:

  pipe.prefetch1  serial chunk loop vs prefetch depth 1
  pipe.prefetch2  serial chunk loop vs prefetch depth 2

These are LOOP lanes: the whole K-chunk pipeline is timed (the
per-chunk readback is the sync), not one jitted call — the quantity
under test is exactly the overlap, so the result cross-check compares
the summed counts and the speedup column is the tracked overlap win.

Timing protocol: each lane jit-compiles both paths, runs one warmup
call (compile + first-touch excluded), then reports the BEST of
``--repeat`` timed calls with ``block_until_ready`` inside the clock —
best-of is the standard microbenchmark estimator for a quantity whose
noise is strictly additive.  ``--smoke`` shrinks sizes/repeat to prove
both paths RUN (tools/static_checks.py wires it into tier-1; speed
assertions only make sense on a real accelerator, see BENCH notes).

Exit 0 when every lane ran both paths and produced matching results
(each lane cross-checks new vs old output before timing — a
microbenchmark that races a wrong answer is worse than none); exit 1
otherwise.

``--calibrate`` skips the lanes and instead measures THIS backend's
peak dense FLOP/s (f32 matmul) and memory bandwidth (elementwise
stream), merge-writing them into ``configs/platform_peaks.json`` keyed
by lowercased device_kind — the per-platform constants ``ndsreport
analyze``'s predicted-time/roofline columns and the executors' scan
roofline consult ahead of the datasheet builtins.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_SIZES = (1 << 12, 1 << 16, 1 << 20)
SMOKE_SIZES = (256, 1024, 4096)


def _best_ms(fn, args, repeat: int) -> float:
    """Best-of-N wall-clock of one compiled call, result synchronized
    inside the clock."""
    import jax
    out = fn(*args)
    jax.block_until_ready(out)  # warmup: compile + first-touch
    best = float("inf")
    for _ in range(repeat):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    return best


def _jit(fn):
    import jax
    return jax.jit(fn)


# ------------------------------------------------------------ lanes
#
# Each lane returns (old_fn, new_fn, args, check) where check(old_out,
# new_out) raises on mismatch. The OLD paths replicate the device
# executor's formulations operator-by-operator (sort+probe, flat-sort
# expansion, segment scatter) so the comparison is against what r02
# actually ran, not a strawman.

def lane_join_unique(n: int, rng):
    import jax.numpy as jnp
    from nds_tpu.engine import kernels as KX
    from nds_tpu.engine.device_exec import _Trace
    dom = max(n // 2, 4)
    bkey = jnp.asarray(rng.permutation(dom)[: max(dom // 2, 2)]
                       .astype(np.int32))
    bok = jnp.ones(bkey.shape, bool)
    pkey = jnp.asarray(rng.integers(0, dom, n).astype(np.int32))
    pok = jnp.ones(n, bool)

    def old(bk, bo, pk, po):
        ks, order = _Trace._build_lookup(bk, bo)
        return _Trace._probe(ks, order, pk, po)

    def new(bk, bo, pk, po):
        return KX.direct_lookup_join(bk, bo, pk, po, 0, dom)

    def check(o, nw):
        np.testing.assert_array_equal(np.asarray(o[1]), np.asarray(nw[1]))
        np.testing.assert_array_equal(
            np.asarray(o[0])[np.asarray(o[1])],
            np.asarray(nw[0])[np.asarray(nw[1])])

    return old, new, (bkey, bok, pkey, pok), check


def lane_join_tiny(n: int, rng):
    import jax.numpy as jnp
    from nds_tpu.engine import kernels as KX
    from nds_tpu.engine.device_exec import _Trace
    nb = min(KX.MATMUL_MAX_BUILD // 2, 32)
    bkey = jnp.asarray((rng.permutation(4 * nb)[:nb]).astype(np.int32))
    bok = jnp.ones(nb, bool)
    pkey = jnp.asarray(rng.integers(0, 4 * nb, n).astype(np.int32))
    pok = jnp.ones(n, bool)

    def old(bk, bo, pk, po):
        ks, order = _Trace._build_lookup(bk, bo)
        return _Trace._probe(ks, order, pk, po)

    def new(bk, bo, pk, po):
        return KX.matmul_probe_join(bk, bo, pk, po)

    def check(o, nw):
        np.testing.assert_array_equal(np.asarray(o[1]), np.asarray(nw[1]))
        np.testing.assert_array_equal(
            np.asarray(o[0])[np.asarray(o[1])],
            np.asarray(nw[0])[np.asarray(nw[1])])

    return old, new, (bkey, bok, pkey, pok), check


def lane_join_mn(n: int, rng):
    import jax.numpy as jnp
    from jax import lax
    from nds_tpu.engine import kernels as KX
    from nds_tpu.engine.device_exec import _ss
    # ~4 matches per key on both sides, q21's self-join shape
    nkeys = max(n // 4, 2)
    lkey = jnp.asarray(rng.integers(0, nkeys, n).astype(np.int32))
    rkey = jnp.asarray(rng.integers(0, nkeys, n).astype(np.int32))
    lok = jnp.ones(n, bool)
    rok = jnp.ones(n, bool)
    K = 8 * n

    def old(lk, lo, rk, ro):
        # the generic M:N formulation from _Trace._run_join: one flat
        # build sort, match ranges via two searchsorteds, cumsum
        # offsets -> slot->pair search at capacity K
        sentinel = jnp.iinfo(lk.dtype).max
        k = jnp.where(lo, lk, sentinel)
        iota = jnp.arange(n, dtype=jnp.int32)
        ks, order = lax.sort([k, iota], num_keys=1, is_stable=True)
        lo_i = _ss(ks, rk, side="left")
        hi_i = _ss(ks, rk, side="right")
        cnt = jnp.where(ro, hi_i - lo_i, 0).astype(jnp.int64)
        offs = jnp.cumsum(cnt)
        total = offs[-1]
        slots = jnp.arange(K, dtype=jnp.int32)
        offsc = jnp.minimum(offs, K + 1).astype(jnp.int32)
        ridx = jnp.clip(_ss(offsc, slots, side="right"), 0, n - 1)
        prev = jnp.where(ridx > 0, jnp.take(offsc,
                                            jnp.maximum(ridx - 1, 0)), 0)
        lpos = jnp.clip(jnp.take(lo_i, ridx) + (slots - prev), 0, n - 1)
        lidx = jnp.take(order, lpos)
        present = slots < jnp.minimum(total, K)
        return lidx, ridx, present, jnp.maximum(total - K, 0)

    def new(lk, lo, rk, ro):
        return KX.partitioned_mn_join(lk, lo, rk, ro, K, 2.0)

    def check(o, nw):
        # same matched multiset (order differs by construction): no
        # overflow on either path, equal match counts, and every
        # emitted pair actually joins
        assert int(o[3]) == 0 and int(nw[3]) == 0
        assert int(np.asarray(o[2]).sum()) == int(np.asarray(nw[2]).sum())
        li, ri, pr = (np.asarray(nw[0]), np.asarray(nw[1]),
                      np.asarray(nw[2]))
        lk_h, rk_h = np.asarray(lkey), np.asarray(rkey)
        assert (lk_h[li[pr]] == rk_h[ri[pr]]).all()

    return old, new, (lkey, lok, rkey, rok), check


def lane_semi(n: int, rng):
    import jax.numpy as jnp
    from nds_tpu.engine import kernels as KX
    from nds_tpu.engine.device_exec import _Trace
    dom = max(n // 2, 4)
    bkey = jnp.asarray(rng.integers(0, dom, n).astype(np.int32))
    bok = jnp.ones(n, bool)
    pkey = jnp.asarray(rng.integers(0, dom, n).astype(np.int32))
    pok = jnp.ones(n, bool)

    def old(bk, bo, pk, po):
        ks, order = _Trace._build_lookup(bk, bo)
        _idx, hit = _Trace._probe(ks, order, pk, po)
        return hit

    def new(bk, bo, pk, po):
        return KX.bitmask_semi(bk, bo, pk, po, 0, dom)

    def check(o, nw):
        np.testing.assert_array_equal(np.asarray(o), np.asarray(nw))

    return old, new, (bkey, bok, pkey, pok), check


def lane_agg_minmax(n: int, rng):
    import jax
    import jax.numpy as jnp
    from nds_tpu.engine import kernels as KX
    G = max(n // 16, 1)
    gid_np = np.sort(rng.integers(0, G, n)).astype(np.int32)
    gid = jnp.asarray(gid_np)
    data = jnp.asarray(rng.integers(0, 1 << 20, n).astype(np.int32))
    # first sorted row of each group (the executor's starts2 shape:
    # one entry per group, empty groups collapse onto the next start)
    starts_np = np.searchsorted(gid_np, np.arange(G)).astype(np.int32)
    starts2 = jnp.asarray(starts_np)

    def old(d, g):
        return jax.ops.segment_min(d, g, num_segments=G,
                                   indices_are_sorted=True)

    def new(d, g):
        return KX.seg_reduce_at_ends(jnp.minimum, d, g, starts2)

    def check(o, nw):
        # compare group minima on POPULATED groups only (segment_min
        # fills empty groups with the dtype max, the scan path's end
        # gather lands on an arbitrary neighboring run there)
        exp, got = np.asarray(o), np.asarray(nw)
        nxt = np.append(starts_np[1:], n)
        pop = nxt > starts_np
        np.testing.assert_array_equal(got[pop], exp[pop])

    return old, new, (data, gid), check


def lane_sort_width(n: int, rng):
    import jax.numpy as jnp
    from jax import lax
    keys32 = jnp.asarray(rng.integers(0, 1 << 30, n).astype(np.int32))
    keys64 = keys32.astype(jnp.int64)
    iota = jnp.arange(n, dtype=jnp.int32)

    def old(k):
        return lax.sort([k, iota], num_keys=1, is_stable=True)

    def new(k):
        return lax.sort([k, iota], num_keys=1, is_stable=True)

    def check(o, nw):
        np.testing.assert_array_equal(np.asarray(o[0]).astype(np.int64),
                                      np.asarray(nw[0]).astype(np.int64))

    # old lane times the int64 sort, new lane the int32 sort — args
    # differ per lane, so wrap them in closures over their own key
    return (lambda: old(keys64)), (lambda: new(keys32)), (), check


def lane_enc_bitpack(n: int, rng):
    """Encoded-vs-raw filter scan (nds_tpu/columnar/): the same range
    predicate over an int64 column read RAW vs read as 16-bit fields
    bit-packed into int32 words with the unpack fused into the filter
    — the lane measures whether moving 1/4 the bytes beats the extra
    shift/mask ALU."""
    from nds_tpu.engine import device_exec  # noqa: F401 -- x64 on
    import jax.numpy as jnp
    from nds_tpu.columnar import device as cdev
    from nds_tpu.columnar.encodings import EncSpec, encode_values
    vals = rng.integers(10_000, 40_000, n).astype(np.int64)
    spec = EncSpec("bitpack", n, "int64", bits=16, lo=10_000)
    words = jnp.asarray(encode_values(spec, vals)[""])
    raw = jnp.asarray(vals)
    lo_q, hi_q = 15_000, 25_000

    # both paths take BOTH buffer sets as real jit arguments (each
    # ignores the other's): a zero-arg closure would let XLA constant-
    # fold the whole scan at compile time and time nothing
    def old(v, _w):
        return jnp.sum((v >= lo_q) & (v < hi_q))

    def new(_v, w):
        dv, _ = cdev.decode(spec, {"k": w}, "k")
        return jnp.sum((dv >= lo_q) & (dv < hi_q))

    def check(o, nw):
        assert int(o) == int(nw), (int(o), int(nw))

    return old, new, (raw, words), check


def lane_enc_rle(n: int, rng):
    """Encoded-vs-raw scan of a SORTED fact column (the RLE shape:
    date / surrogate-key columns): a date-range count over the raw
    int32 column vs the run-length form (run values + run starts;
    run ids rebuilt by scatter + prefix sum, fused into the count)."""
    import jax.numpy as jnp
    from nds_tpu.columnar import device as cdev
    from nds_tpu.columnar.encodings import plan_values, encode_values
    # ~64 rows per run (a clustered fact date column): the RLE form
    # must actually be smaller at every benchmarked size
    dom = max(n // 64, 4)
    vals = (np.sort(rng.integers(0, dom, n)).astype(np.int32)
            + np.int32(10_000))
    spec = plan_values(vals, mode="rle")
    assert spec is not None and spec.kind == "rle"
    enc = encode_values(spec, vals)
    rv, ends = jnp.asarray(enc[""]), jnp.asarray(enc["#x"])
    raw = jnp.asarray(vals)
    lo_q, hi_q = 10_000 + dom // 4, 10_000 + dom // 2

    def old(v, _r, _e):
        return jnp.sum((v >= lo_q) & (v < hi_q))

    def new(_v, r, e):
        dv, _ = cdev.decode(spec, {"k": r, "k#x": e}, "k")
        return jnp.sum((dv >= lo_q) & (dv < hi_q))

    def check(o, nw):
        assert int(o) == int(nw), (int(o), int(nw))

    return old, new, (raw, rv, ends), check


def lane_enc_dictjoin(n: int, rng):
    """Dict-code join, raw vs packed codes: today's engine probes
    direct-address joins with int32 dictionary codes; under the
    columnar store the probe side's codes arrive bit-packed and
    unpack INTO the gather. Same join, 1/2-1/4 the probe bytes."""
    import jax.numpy as jnp
    from nds_tpu.columnar import device as cdev
    from nds_tpu.columnar.encodings import EncSpec, encode_values
    from nds_tpu.engine import kernels as KX
    dom = 4096  # dictionary size -> 16-bit codes
    bkey = jnp.asarray(rng.permutation(dom)[:dom // 2]
                       .astype(np.int32))
    bok = jnp.ones(bkey.shape, bool)
    pcodes = rng.integers(0, dom, n).astype(np.int32)
    spec = EncSpec("bitpack", n, "int32", bits=16, lo=0)
    pwords = jnp.asarray(encode_values(spec, pcodes)[""])
    praw = jnp.asarray(pcodes)
    pok = jnp.ones(n, bool)

    def old(bk, bo, pk, _pw, po):
        return KX.direct_lookup_join(bk, bo, pk, po, 0, dom)

    def new(bk, bo, _pk, pw, po):
        pk, _ = cdev.decode(spec, {"k": pw}, "k")
        return KX.direct_lookup_join(bk, bo, pk, po, 0, dom)

    def check(o, nw):
        np.testing.assert_array_equal(np.asarray(o[1]),
                                      np.asarray(nw[1]))
        np.testing.assert_array_equal(
            np.asarray(o[0])[np.asarray(o[1])],
            np.asarray(nw[0])[np.asarray(nw[1])])

    return old, new, (bkey, bok, praw, pwords, pok), check


def _lane_pipe(depth: int):
    """Phase-A pipeline lane at one prefetch depth: an 8-chunk stream
    where each chunk's HOST half (bitpack encode, pure numpy — releases
    the GIL) and DEVICE half (fused decode + range-filter count over
    the one compiled program, per-chunk readback as the sync point)
    mirror the chunked engine's keep-mask loop. ``old`` runs the
    serial loop (depth 0 = byte-identical staging inline), ``new`` the
    double-buffered one — the speedup IS the measured overlap."""

    def build(n: int, rng):
        from nds_tpu.engine import device_exec  # noqa: F401 -- x64 on
        import jax
        import jax.numpy as jnp
        from nds_tpu.columnar import device as cdev
        from nds_tpu.columnar.encodings import EncSpec, encode_values
        from nds_tpu.engine.pipeline_io import ChunkPrefetcher
        K = 8
        chunks = [rng.integers(10_000, 40_000, n).astype(np.int64)
                  for _ in range(K)]
        spec = EncSpec("bitpack", n, "int64", bits=16, lo=10_000)
        lo_q, hi_q = 15_000, 25_000

        def count(w):
            dv, _ = cdev.decode(spec, {"k": w}, "k")
            return jnp.sum((dv >= lo_q) & (dv < hi_q))

        compiled = jax.jit(count)

        def stage(i):
            words = encode_values(spec, chunks[i])[""]
            return jax.device_put(words), words.nbytes

        def run_with(d: int) -> int:
            total = 0
            pf = ChunkPrefetcher(range(K), stage, d)
            try:
                for staged in pf:
                    try:
                        total += int(compiled(staged.payload))
                    finally:
                        staged.release()
            finally:
                pf.close()
            return total

        def old():
            return run_with(0)

        def new():
            return run_with(depth)

        def check(o, nw):
            assert int(o) == int(nw), (int(o), int(nw))

        return old, new, (), check

    return build


LANES = {
    "join.unique": lane_join_unique,
    "join.tiny": lane_join_tiny,
    "join.mn": lane_join_mn,
    "semi": lane_semi,
    "agg.minmax": lane_agg_minmax,
    "sort.width": lane_sort_width,
    "enc.bitpack": lane_enc_bitpack,
    "enc.rle": lane_enc_rle,
    "enc.dictjoin": lane_enc_dictjoin,
    "pipe.prefetch1": _lane_pipe(1),
    "pipe.prefetch2": _lane_pipe(2),
}

# lanes whose old/new callables run a whole chunk LOOP (syncing
# internally): timed as-is, never wrapped in an outer jax.jit
LOOP_LANES = {"pipe.prefetch1", "pipe.prefetch2"}


def calibrate(smoke: bool = False,
              out_path: "str | None" = None) -> dict:
    """Measure THIS backend's peak dense FLOP/s (f32 matmul, the MXU
    saturator) and memory bandwidth (elementwise read+write stream),
    and merge them into ``configs/platform_peaks.json`` keyed by
    lowercased device_kind — the measured constants analyze's
    predicted-time model and the executors' roofline denominator
    consult ahead of the datasheet builtins (obs/costs.platform_peaks,
    device_exec._peak_mem_gbps)."""
    import jax
    import jax.numpy as jnp

    from nds_tpu.obs import costs as obs_costs
    n = 512 if smoke else 2048
    reps = 2 if smoke else 5
    a = jnp.ones((n, n), jnp.float32)
    mm = _jit(lambda x: x @ x)
    mm_ms = _best_ms(mm, (a,), reps)
    flops = (2.0 * n ** 3) / (mm_ms / 1000.0)
    m = (1 << 20) if smoke else (1 << 26)   # f32 elements streamed
    v = jnp.ones((m,), jnp.float32)
    stream = _jit(lambda x: x + 1.0)        # reads + writes the array
    st_ms = _best_ms(stream, (v,), reps)
    gbps = (2.0 * v.nbytes) / (st_ms / 1000.0) / 1e9
    kind = str(jax.devices()[0].device_kind).lower()
    path = out_path or obs_costs.peaks_path()
    peaks = dict(obs_costs.calibrated_peaks())  # merge, don't clobber
    peaks[kind] = {"flops": round(flops, 3), "mem_gbps": round(gbps, 3)}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from nds_tpu.io.integrity import write_json_atomic
    write_json_atomic(path, peaks)
    return {"device_kind": kind, "path": path, **peaks[kind]}


def run(sizes, repeat: int, lanes=None) -> dict:
    import jax
    rng = np.random.default_rng(20260803)
    results = []
    failures = []
    for name, build in LANES.items():
        if lanes and name not in lanes:
            continue
        for n in sizes:
            old_fn, new_fn, args, check = build(int(n), rng)
            if name in LOOP_LANES:
                jold, jnew = old_fn, new_fn
            else:
                jold, jnew = _jit(old_fn), _jit(new_fn)
            try:
                o, nw = jold(*args), jnew(*args)
                jax.block_until_ready((o, nw))
                check(o, nw)
            except Exception as exc:  # noqa: BLE001 - recorded + exit 1
                failures.append({"op": name, "size": int(n),
                                 "error": f"{type(exc).__name__}: {exc}"})
                continue
            old_ms = _best_ms(jold, args, repeat)
            new_ms = _best_ms(jnew, args, repeat)
            results.append({
                "op": name, "size": int(n),
                "old_ms": round(old_ms, 4), "new_ms": round(new_ms, 4),
                "speedup": round(old_ms / new_ms, 3) if new_ms else None,
            })
    return {
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "repeat": repeat,
        "results": results,
        "failures": failures,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sizes", default=None,
                    help="comma-separated row counts "
                         f"(default {','.join(map(str, DEFAULT_SIZES))})")
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--lanes", default=None,
                    help=f"comma-separated lane subset "
                         f"(known: {','.join(LANES)})")
    ap.add_argument("--out", default=None, help="write JSON here "
                    "(stdout always gets the document)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes, 1 repeat: prove both paths run "
                         "(the static_checks tier-1 wiring)")
    ap.add_argument("--calibrate", action="store_true",
                    help="measure this backend's peak FLOPs/bandwidth "
                         "and write configs/platform_peaks.json "
                         "(consumed by ndsreport analyze's "
                         "predicted-time model); skips the lane runs")
    args = ap.parse_args(argv)
    if args.calibrate:
        cal = calibrate(smoke=args.smoke, out_path=args.out)
        print(json.dumps(cal, indent=2))
        print(f"CALIBRATED {cal['device_kind']}: "
              f"{cal['flops'] / 1e12:.3f} TFLOP/s, "
              f"{cal['mem_gbps']:.1f} GB/s -> {cal['path']}")
        return 0
    sizes = (SMOKE_SIZES if args.smoke and not args.sizes
             else tuple(int(s) for s in
                        (args.sizes or
                         ",".join(map(str, DEFAULT_SIZES))).split(",")))
    repeat = 1 if args.smoke else args.repeat
    lanes = set(args.lanes.split(",")) if args.lanes else None
    if lanes:
        unknown = lanes - set(LANES)
        if unknown:
            print(f"unknown lane(s): {sorted(unknown)}")
            return 2
    doc = run(sizes, repeat, lanes)
    text = json.dumps(doc, indent=2)
    print(text)
    if args.out:
        from nds_tpu.io.integrity import write_json_atomic
        write_json_atomic(args.out, doc)
    if doc["failures"]:
        print(f"NDSPERF FAILED: {len(doc['failures'])} lane(s) broke "
              f"or mismatched")
        return 1
    slow = [r for r in doc["results"]
            if r["speedup"] is not None and r["speedup"] < 1.0]
    if slow:
        # informational on CPU (the old paths are CPU-tuned); the
        # acceptance criterion is evaluated on a real accelerator
        print(f"ndsperf note: {len(slow)} lane/size point(s) where the "
              f"new kernel is not faster on backend="
            f"{doc['backend']}")
    print(f"NDSPERF OK: {len(doc['results'])} point(s) on "
          f"{doc['backend']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
