"""Plan-and-verify every NDS / NDS-H statement on CPU — no accelerator.

Planning is pure Python (parser + planner + catalog), so the full
invariant sweep over all 103 NDS statements (99 templates; q14/q23/
q24/q39 are two-statement) and 22 NDS-H SELECTs runs in seconds on any
host. This is the static half of the correctness story: the
differential tiers prove the *results*, this proves the *plans* — and
it runs in tier-1 (tests/test_static_analysis.py) so a planner
regression fails before any engine executes it.

Every verified statement also gets a PLACEMENT assigned by the
scheduler's cost model (engine/scheduler.py) seeded from the plan
verifier's size estimates over the catalog's SF1 statistics — proving
the control-plane decision the unified pipeline makes per query is
computable for the whole workload with no accelerator and no data. A
statement the cost model cannot place is a failure.

Exit 0 when every statement plans, verifies clean, and places; prints
each violation otherwise. View DDL (NDS-H q15's create/drop cycle) is
applied to the session, not verified as a plan.

Usage: python tools/ndsverify.py [--suite nds|nds_h|all] [-v]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from nds_tpu.analysis import plan_verify  # noqa: E402
from nds_tpu.engine.session import Session  # noqa: E402
from nds_tpu.sql import plan as P  # noqa: E402


def _verify_statement(session: Session, label: str, stmt: str,
                      failures: list, placements: dict,
                      verbose: bool = False) -> int:
    """Plan one statement, apply DDL side effects, verify SELECT/INSERT
    plans, and assign a placement via the scheduler cost model.
    Returns the number of PlannedQuery units verified.

    Under NDS_TPU_VERIFY_PLANS=1 (tests force it) Session.plan raises
    on the first violation before our collecting verify() pass runs —
    catch it so one bad statement still reports its violations and the
    sweep continues to the remaining statements."""
    from nds_tpu.engine import scheduler
    try:
        planned = session.plan(stmt)
    except plan_verify.PlanVerifyError as exc:
        for v in exc.violations:
            failures.append(f"{label}: {v}")
        return 1
    if isinstance(planned, tuple):
        action, name, node = planned
        if action == "create_view":
            session.views[name] = node
            session._view_sql[name] = stmt
            return 0
        if action == "drop_view":
            session.views.pop(name, None)
            session._view_sql.pop(name, None)
            return 0
        if action == "insert" and isinstance(node, P.PlannedQuery):
            planned = node
        else:  # delete carries a raw WHERE ast, nothing planned
            return 0
    vs = plan_verify.verify(planned, catalog=session.catalog)
    for v in vs:
        failures.append(f"{label}: {v}")
    try:
        placement, why = scheduler.CostModel().choose(
            planned, scheduler.UNIVERSES["tpu"],
            catalog=session.catalog, qname=label)
        placements[placement] = placements.get(placement, 0) + 1
        if verbose:
            print(f"  {label}: placement={placement} ({why})")
    except Exception as exc:  # noqa: BLE001 - a placement MUST compute
        failures.append(f"{label}: placement assignment failed: "
                        f"{type(exc).__name__}: {exc}")
    return 1


def verify_nds(failures: list, placements: dict,
               verbose: bool = False) -> int:
    from nds_tpu.nds import streams
    session = Session.for_nds()
    n = 0
    for qn in streams.available_templates():
        sql = streams.render_query(qn)
        parts = [s for s in sql.split(";") if s.strip()]
        for i, stmt in enumerate(parts, 1):
            label = f"nds q{qn}" + (f" part{i}" if len(parts) > 1 else "")
            n += _verify_statement(session, label, stmt, failures,
                                   placements, verbose)
    return n


def verify_nds_h(failures: list, placements: dict,
                 verbose: bool = False) -> int:
    from nds_tpu.nds_h import streams
    session = Session.for_nds_h()
    n = 0
    for qn in streams.stream_order(0):
        for i, stmt in enumerate(streams.statements(qn), 1):
            label = f"nds_h q{qn} part{i}"
            n += _verify_statement(session, label, stmt, failures,
                                   placements, verbose)
    return n


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--suite", choices=("nds", "nds_h", "all"),
                    default="all")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    failures: list[str] = []
    placements: dict[str, int] = {}
    counts = []
    if args.suite in ("nds", "all"):
        counts.append(("nds", verify_nds(failures, placements,
                                         args.verbose)))
    if args.suite in ("nds_h", "all"):
        counts.append(("nds_h", verify_nds_h(failures, placements,
                                             args.verbose)))
    for line in failures:
        print(line)
    total = sum(n for _name, n in counts)
    placed = sum(placements.values())
    if placed != total and not failures:
        print(f"FAIL: only {placed}/{total} statements got a placement")
        return 1
    summary = " + ".join(f"{n} {name}" for name, n in counts)
    pl = ", ".join(f"{k}={v}" for k, v in sorted(placements.items()))
    print(f"{'FAIL' if failures else 'OK'}: {len(failures)} "
          f"violation(s) across {summary} statement(s); "
          f"placements assigned: {placed} ({pl})")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
