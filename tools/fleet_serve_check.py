"""Fleet serving gate: the replicated serve fleet proven end-to-end.

tier-1 (via tools/static_checks.py) launches a REAL multi-process
fleet — gen-warehouse replicas (``python -m nds_tpu.serve.replica``,
``engine.backend=tpu`` compiled by CPU XLA) behind the in-process
FleetRouter + ReplicaSupervisor — and proves the robustness contract
under chaos:

1. **warmup** — 2 replicas admitted; one request per (suite,
   template) through the router pays every compile into the SHARED
   AOT plan store;
2. **scale-out** — a third replica started AFTER warmup is
   health-probed and admitted, warm from the shared store;
3. **chaos load** — mixed NDS + NDS-H literal-variant requests at
   >= 40 concurrency while one replica is SIGKILLed mid-load and
   another is SIGTERMed (drain -> exit 75 -> warm resume): every
   request completes OK, traffic redistributes (the late joiner
   answers, redeliveries > 0, ejections > 0);
4. **zero loss / zero double** — the request journal accounts for
   every accepted request exactly once;
5. **re-admission** — both disturbed replicas come back (restart and
   resume respectively) and are re-admitted by health probe; the
   fleet answers afterward;
6. **oracle parity** — every response digest equals a sequential
   single-engine replay of the same statements (deterministic seeded
   datagen: the gate's oracle warehouse is bit-identical to every
   replica's);
7. **zero warm compiles** — final heartbeat snapshots of ALL live
   replicas (two post-chaos incarnations + the late joiner — every
   one a process started after warmup) show compiles_total == 0 and
   compile_cache_misses_total == 0, while the warmup incarnations
   provably compiled (counter-wired check); the plan-cache entry
   count is unchanged by the literal variants;
8. **observability** — per-request summaries are schema-clean with
   replica attribution and ``ndsreport analyze`` derives the
   per-replica latency rollup.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import ndsload  # noqa: E402
import serve_check  # noqa: E402

SCALE = 0.01
NDS_H_TEMPLATES = (1, 5)
NDS_TEMPLATES = (7, 96)
CONCURRENCY = 44
LOAD_COUNT = 48


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _hb_counters(fleet_dir: str, name: str) -> dict:
    path = os.path.join(fleet_dir, "hb", f"{name}.json")
    try:
        with open(path) as f:
            return dict(json.load(f).get("counters", {}))
    except (OSError, ValueError):
        return {}


async def _load_with_chaos(router, sup, docs: list,
                           concurrency: int) -> list:
    """Drive the mixed load; chaos is keyed on COMPLETION COUNT (not
    wall clock) so the kills provably land mid-load."""
    sem = asyncio.Semaphore(concurrency)
    done = {"n": 0}

    async def one(doc):
        async with sem:
            resp = await router.submit(doc)
        done["n"] += 1
        return resp

    async def chaos():
        while done["n"] < 6:
            await asyncio.sleep(0.05)
        print(f"[gate] SIGKILL r0 at {done['n']} completions",
              flush=True)
        sup.kill("r0")
        while done["n"] < 20:
            await asyncio.sleep(0.05)
        print(f"[gate] SIGTERM r1 (drain) at {done['n']} "
              f"completions", flush=True)
        sup.drain("r1")

    results = await asyncio.gather(chaos(),
                                   *[one(d) for d in docs])
    return results[1:]


async def _run_gate(workdir: str) -> int:
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.serve.fleet import launch_fleet, scale_out
    from nds_tpu.utils.config import EngineConfig

    fleet_dir = os.path.join(workdir, "fleet")
    argv_factory = ndsload.fleet_replica_argv(
        workdir, SCALE, max_queue=64)
    cfg = EngineConfig(overrides={
        "serve.max_queue": "64",
        "serve.fleet.max_pending": "256",
        "serve.fleet.ping_interval_s": "0.25",
        "serve.fleet.ping_timeout_s": "3",
    })
    sup, router = launch_fleet(fleet_dir, ["r0", "r1"],
                               argv_factory, config=cfg,
                               stall_s=10.0)
    sup.start()
    try:
        await router.start()
        # -- 1: two replicas admitted, warmup pays every compile into
        #       the shared AOT store
        if not await router.wait_admitted(2, 300):
            return _fail("initial replicas never admitted: "
                         f"{router.healthy_replicas()}")
        warm = await ndsload.run_router(
            router, ndsload.warmup_docs(7, NDS_H_TEMPLATES,
                                        NDS_TEMPLATES), 2)
        ws = ndsload.summarize(warm)
        if ws["status"].get("ok") != len(warm):
            return _fail(f"warmup not clean: {ws}")
        ocfg = EngineConfig(overrides={
            "cache.dir": os.path.join(workdir, "plancache")})
        entries_warm = serve_check._cache_entry_count(ocfg)
        if entries_warm < len(NDS_H_TEMPLATES) + len(NDS_TEMPLATES):
            return _fail(f"warmup persisted only {entries_warm} "
                         f"plan-cache entries")
        # snapshots lag by up to their interval; give the warmup
        # compiles a beat to land, then prove the counters are WIRED
        # (the zero assertions in phase 7 are meaningless otherwise)
        await asyncio.sleep(1.5)
        warm_compiles = sum(
            _hb_counters(fleet_dir, n).get("compiles_total", 0)
            for n in ("r0", "r1"))
        if warm_compiles <= 0:
            return _fail("warmup incarnations report zero compiles "
                         "— compile counters not wired into "
                         "heartbeat snapshots")
        print(f"OK: warmup {len(warm)} requests, {entries_warm} "
              f"shared plan-cache entries, {warm_compiles} compiles "
              f"across r0+r1")

        # -- 2: scale-out AFTER warmup — the joiner must warm from
        #       the shared store, not recompile
        scale_out(sup, router, fleet_dir, "r2", argv_factory)
        if not await router.wait_admitted(3, 300):
            return _fail(f"late joiner r2 never admitted: "
                         f"{router.healthy_replicas()}")
        print("OK: r2 joined post-warmup and passed health probe")

        # -- 3: chaos load — SIGKILL r0 + drain r1 mid-load at
        #       >= 40 concurrency
        docs = ndsload.build_requests(
            LOAD_COUNT, 11, tenants=3,
            nds_h_templates=NDS_H_TEMPLATES,
            nds_templates=NDS_TEMPLATES)
        resp = await _load_with_chaos(router, sup, docs, CONCURRENCY)
        ls = ndsload.summarize(resp)
        if ls["status"].get("ok") != len(docs):
            return _fail(f"chaos load not fully ok: {ls['status']}")
        by_rep: dict = {}
        for r in resp:
            by_rep[r.get("replica")] = by_rep.get(
                r.get("replica"), 0) + 1
        if len(by_rep) < 2:
            return _fail(f"no redistribution: all answers from "
                         f"{by_rep}")
        if not by_rep.get("r2"):
            return _fail(f"late joiner took no traffic: {by_rep}")
        counters = obs_metrics.snapshot()["counters"]
        if counters.get("fleet_redelivered_total", 0) < 1:
            return _fail("no redeliveries despite mid-load kills")
        if counters.get("fleet_ejections_total", 0) < 1:
            return _fail("no ejections despite SIGKILL")
        print(f"OK: {len(resp)} requests at {CONCURRENCY} "
              f"concurrency through the chaos window; placement "
              f"{by_rep}, "
              f"{counters.get('fleet_redelivered_total', 0):g} "
              f"redelivered, "
              f"{counters.get('fleet_ejections_total', 0):g} "
              f"ejections")

        # -- 4: the journal proves zero lost / zero double
        jv = router.journal.verify()
        if jv["lost"] or jv["double"]:
            return _fail(f"journal not clean: {jv}")
        if jv["settled"] < len(docs) + len(warm):
            return _fail(f"journal settled {jv['settled']} < "
                         f"{len(docs) + len(warm)} accepted")
        print(f"OK: journal {jv['settled']}/{jv['accepted']} "
              f"settled, 0 lost, 0 double-answered")

        # -- 5: both disturbed replicas come back and the fleet
        #       answers afterward
        deadline = time.time() + 240
        while time.time() < deadline:
            if {"r0", "r1", "r2"} <= set(router.healthy_replicas()):
                break
            await asyncio.sleep(0.25)
        else:
            return _fail(f"fleet never re-converged: "
                         f"{router.healthy_replicas()}")
        post = ndsload.build_requests(
            6, 13, tenants=1, nds_h_templates=NDS_H_TEMPLATES,
            nds_templates=NDS_TEMPLATES)
        presp = await ndsload.run_router(router, post, 3)
        ps = ndsload.summarize(presp)
        if ps["status"].get("ok") != len(post):
            return _fail(f"fleet unhealthy after re-admission: {ps}")
        # the plan-cache entry count must not have moved: literal
        # variants + two fresh incarnations + the joiner all share
        # the warmup fingerprints (checked BEFORE the oracle below
        # touches the same store)
        if serve_check._cache_entry_count(ocfg) != entries_warm:
            return _fail(
                f"cache entries moved {entries_warm} -> "
                f"{serve_check._cache_entry_count(ocfg)}")
        print(f"OK: r0 restarted + r1 resumed and re-admitted; "
              f"post-chaos load clean; {entries_warm} cache entries "
              f"unchanged")

        # -- 6: sequential single-engine oracle — deterministic
        #       seeded datagen makes the gate's warehouse
        #       bit-identical to every replica's
        oracle_srv, _ = serve_check._build_server(workdir)
        # the two batches reuse qnames (both count from #0), so each
        # gets its own oracle map — qname keys collide across batches
        for batch_resp, batch_docs in ((resp, docs), (presp, post)):
            oracle = serve_check._oracle_digests(oracle_srv,
                                                 batch_docs)
            for r in batch_resp:
                if r.get("digest") != oracle.get(r.get("qname")):
                    return _fail(f"{r.get('qname')}: served digest "
                                 f"{r.get('digest')} != oracle "
                                 f"{oracle.get(r.get('qname'))} "
                                 f"(replica {r.get('replica')})")
        print(f"OK: {len(resp) + len(presp)} responses "
              f"digest-identical to the sequential oracle")
        return 0
    finally:
        await router.stop()
        fleet_summary = sup.stop()
        # stash for the post-shutdown phases (main reads these)
        _run_gate.summary = fleet_summary  # type: ignore[attr-defined]


def _post_shutdown_checks(workdir: str, summary: dict) -> int:
    """Phases 7-8 run AFTER sup.stop(): the drain path has flushed
    every replica's FINAL heartbeat snapshot and summary files."""
    fleet_dir = os.path.join(workdir, "fleet")
    reps = summary.get("replicas", {})
    r0, r1 = reps.get("r0", {}), reps.get("r1", {})
    if 9 not in r0.get("signals", []) or r0.get("restarts", 0) < 1:
        return _fail(f"r0 SIGKILL/restart not recorded: {r0}")
    if 75 not in r1.get("exit_codes", []) or r1.get("resumes",
                                                    0) < 1:
        return _fail(f"r1 drain->75->resume not recorded: {r1}")

    # -- 7: zero compiles on every final incarnation — all three are
    #       processes started after warmup, warm from the shared store
    for name in ("r0", "r1", "r2"):
        c = _hb_counters(fleet_dir, name)
        if not c:
            return _fail(f"{name}: no final heartbeat snapshot")
        if c.get("compiles_total", 0) != 0:
            return _fail(f"{name}: final incarnation compiled "
                         f"{c['compiles_total']:g} programs "
                         f"(should be warm from the shared store)")
        if c.get("compile_cache_misses_total", 0) != 0:
            return _fail(f"{name}: final incarnation missed the "
                         f"plan cache "
                         f"{c['compile_cache_misses_total']:g}x")
    print("OK: 0 compiles / 0 plan-cache misses on every "
          "post-warmup incarnation (r0#r1, r1#r1, late joiner r2)")

    # -- 8: summaries are schema-clean with replica attribution and
    #       analyze derives the per-replica rollup
    import check_trace_schema
    from nds_tpu.obs import analyze
    sdir = os.path.join(workdir, "serve_json")
    files = [f for f in os.listdir(sdir) if f.endswith(".json")]
    errs: list = []
    for f in files:
        errs.extend(check_trace_schema.validate_summary_file(
            os.path.join(sdir, f)))
    if errs:
        return _fail(f"summary schema errors: {errs[:3]}")
    analysis = analyze.analyze_run(sdir)
    rollup = analysis.get("replicas") or {}
    if len(rollup) < 2:
        return _fail(f"analyze derived no per-replica rollup: "
                     f"{rollup}")
    if any("p99_ms" not in q for q in rollup.values()):
        return _fail(f"replica rollup missing quantiles: {rollup}")
    print(f"OK: {len(files)} schema-clean summaries; analyze "
          f"per-replica p99: "
          f"{ {n: q.get('p99_ms') for n, q in rollup.items()} }")
    return 0


def main(argv=None) -> int:
    with tempfile.TemporaryDirectory(
            prefix="nds_fleet_check_") as wd:
        rc = asyncio.run(_run_gate(wd))
        if rc == 0:
            rc = _post_shutdown_checks(
                wd, getattr(_run_gate, "summary", {}))
    print("FLEET SERVE CHECK", "OK" if rc == 0 else "FAILED")
    return rc


if __name__ == "__main__":
    sys.exit(main())
