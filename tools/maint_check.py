"""Crash-safe writable-warehouse gate: chaos-proven full-bench metric.

tier-1 (via tools/static_checks.py) proves the delta-segment writable
warehouse (nds_tpu/columnar/delta.py, journaled maintenance in
nds_tpu/nds/maintenance.py; README "Benchmark phases") end-to-end:

1. **full sweep + mid-maintenance SIGKILL** — a real
   ``python -m nds_tpu.nds.bench`` run (SF0.01, 3-query streams)
   executes load -> power -> throughput -> maintenance -> validate ->
   metric. A ``dml.apply`` fault injection wedges LF_WS mid-round-1 and
   the whole process group is SIGKILLed — the unjournaled crash, not a
   graceful drain.
2. **resume, zero double-applies** — ``bench --resume`` replays the
   journaled phases and the maintenance commit journal: every function
   committed before the kill keeps ``starts == [0]`` (incarnation 0,
   never re-applied), the victim re-runs exactly once, and both rounds
   end with all 11 LF_*/DF_* functions done. The composite metric folds
   both Tdm terms.
3. **validate phase** — the resumed bench's validate phase re-runs the
   power stream on the maintained warehouse against a CPU oracle and
   must match (``validation_ok``), proving the journal accounting above
   with results, not bookkeeping.
4. **encoded store survives maintenance** — the snapshot lineage of
   every mutated table still references its BASELINE part files plus
   ``_v*/`` delta segments (base encoded columns never rewritten), a
   device-placement run over the maintained warehouse digest-matches a
   fresh CPU oracle, and every device summary reports
   ``compression_ratio > 1``.
5. **rollback restores pre-maintenance bytes** — manifest truncation
   (nds/rollback.py) then a power re-run reproduces the original power
   phase's result digests byte-identically.
6. **invalidation scope** — a DML insert into one table evicts only
   plans scanning it: an unrelated query keeps its plan-cache entry and
   re-runs with ZERO compiles; the mutated table's query reflects the
   new rows.
"""

from __future__ import annotations

import csv
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SCALE = 0.01
TEMPLATES = [96, 7, 93]   # store_sales-heavy: maintenance moves them
VICTIM = "LF_WS"
# wedge LF_WS's INSERT inside dml.apply (scope matches the ctx table
# value "web_sales"; times defaults to 1 so only the first match hangs)
FAULT = "dml.apply:hang=120@web_sales"
WAIT_S = 240


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _tail(path: str, n: int = 30) -> str:
    try:
        with open(path, errors="replace") as f:
            return "".join(f.readlines()[-n:])
    except OSError:
        return "<no log>"


def _read_journal(path: str) -> dict:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    return doc.get("queries", {}) if isinstance(doc, dict) else {}


def _digests(json_dir: str) -> dict:
    q = _read_journal(os.path.join(json_dir, "power-nds_queries.json"))
    return {name: e.get("result_digest") for name, e in q.items()}


def _write_cfg(wd: str) -> str:
    import yaml
    cfg = {
        "scale_factor": SCALE,
        "parallel": 1,
        "num_streams": 1,        # -> 3 streams: power + 1 per half
        "backend": "cpu",
        "paths": {
            "raw_data": os.path.join(wd, "raw"),
            "refresh_data": os.path.join(wd, "refresh"),
            "warehouse": os.path.join(wd, "wh"),
            "streams": os.path.join(wd, "streams"),
            "reports": os.path.join(wd, "reports"),
        },
        "validate": {"epsilon": 0.00001},
        # streams are pre-generated with the 3 maintenance-sensitive
        # templates — the full 99-template sweep belongs to the slow
        # orchestrator test, not a tier-1 gate
        "skip": {"stream_gen": True},
    }
    path = os.path.join(wd, "bench.yml")
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    return path


def _env(faults: str | None = None) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [ROOT] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    env.pop("NDS_TPU_FAULTS", None)
    if faults:
        env["NDS_TPU_FAULTS"] = faults
    return env


def _funcs():
    from nds_tpu.nds import maintenance
    return (maintenance.INSERT_FUNCS + maintenance.DELETE_FUNCS
            + maintenance.INVENTORY_DELETE_FUNCS)


def _bench_kill_resume(wd: str) -> int:
    """Sections 1-3: the chaos bench run, resume accounting, validate
    phase, and the composite metric with both Tdm terms folded in."""
    from nds_tpu.nds import maintenance
    from nds_tpu.nds.streams import generate_query_streams

    sdir = os.path.join(wd, "streams")
    generate_query_streams(sdir, 3, templates=TEMPLATES,
                           qualification=False)
    cfg_path = _write_cfg(wd)
    wh = os.path.join(wd, "wh")
    jpath = maintenance.journal_path(wh, os.path.join(wd, "refresh1"))

    log1 = os.path.join(wd, "bench1.log")
    cmd = [sys.executable, "-m", "nds_tpu.nds.bench", cfg_path]
    with open(log1, "w") as lf:
        proc = subprocess.Popen(cmd, cwd=ROOT, env=_env(faults=FAULT),
                                stdout=lf, stderr=subprocess.STDOUT,
                                start_new_session=True)
        deadline = time.time() + WAIT_S
        wedged = False
        while time.time() < deadline:
            if proc.poll() is not None:
                return _fail(
                    f"bench exited (rc={proc.returncode}) before the "
                    f"{VICTIM} fault wedged it:\n{_tail(log1)}")
            q = _read_journal(jpath)
            v = q.get(VICTIM, {})
            if v.get("done"):
                return _fail(f"{VICTIM} completed — the dml.apply "
                             f"fault never fired")
            if v.get("starts"):
                wedged = True
                break
            time.sleep(0.3)
        if not wedged:
            proc.kill()
            return _fail(f"bench never reached {VICTIM} within "
                         f"{WAIT_S}s:\n{_tail(log1)}")
        time.sleep(0.5)  # let the statement reach the hang site
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait()

    funcs = _funcs()
    before = _read_journal(jpath)
    committed_before = [f for f in funcs if before.get(f, {}).get("done")]
    if not committed_before:
        return _fail("kill landed before any maintenance function "
                     "committed — the chaos window missed")
    if before.get(VICTIM, {}).get("done"):
        return _fail(f"{VICTIM} journaled done before the kill")
    print(f"OK: SIGKILL mid-maintenance with "
          f"{len(committed_before)}/{len(funcs)} functions committed, "
          f"{VICTIM} in flight")

    log2 = os.path.join(wd, "bench2.log")
    with open(log2, "w") as lf:
        rc = subprocess.run(cmd + ["--resume"], cwd=ROOT, env=_env(),
                            stdout=lf, stderr=subprocess.STDOUT,
                            timeout=WAIT_S * 2).returncode
    if rc != 0:
        return _fail(f"bench --resume exited {rc}:\n{_tail(log2)}")

    # journal accounting: zero double-applied mutations
    after = _read_journal(jpath)
    for fname in funcs:
        e = after.get(fname, {})
        if not e.get("done"):
            return _fail(f"round 1: {fname} not done after resume")
    for fname in committed_before:
        e = after[fname]
        if e.get("starts") != [0] or e.get("incarnation") != 0:
            return _fail(
                f"round 1: {fname} was re-applied after resume "
                f"(starts={e.get('starts')}, "
                f"incarnation={e.get('incarnation')}) — journal must "
                f"replay committed functions, never re-run them")
    ve = after[VICTIM]
    if len(ve.get("starts", [])) != 2:
        return _fail(f"round 1: {VICTIM} starts={ve.get('starts')} — "
                     f"expected exactly one pre-kill + one resume start")
    j2 = _read_journal(maintenance.journal_path(
        wh, os.path.join(wd, "refresh2")))
    redone = [f for f in funcs if not j2.get(f, {}).get("done")]
    if redone:
        return _fail(f"round 2 incomplete after resume: {redone}")
    print(f"OK: resume — {len(funcs)} functions done both rounds, "
          f"{len(committed_before)} replayed from journal untouched, "
          f"{VICTIM} re-ran exactly once")

    # the resumed run's validate phase compared the maintained
    # warehouse against a CPU oracle and the metric folded both Tdm
    with open(os.path.join(wd, "reports", "bench_state.json")) as f:
        phases = json.load(f).get("phases", {})
    for ph in ("power_test", "throughput_1", "maintenance_1",
               "throughput_2", "maintenance_2", "validate"):
        if ph not in phases:
            return _fail(f"bench_state.json missing phase {ph}")
    if phases["validate"]["timings"].get("validation_ok") != 1:
        return _fail("validate phase did not pass against the CPU "
                     "oracle on the maintained warehouse")
    with open(os.path.join(wd, "reports", "metrics.csv")) as f:
        row = list(csv.DictReader(f))[0]
    if not row["metric"] or int(row["metric"]) <= 0:
        return _fail(f"composite metric missing: {row!r}")
    for col in ("maintenance1_s", "maintenance2_s"):
        if float(row[col]) <= 0:
            return _fail(f"{col} not folded into the metric: {row!r}")
    print(f"OK: validate phase matched the CPU oracle; metric="
          f"{row['metric']} with Tdm {row['maintenance1_s']}s + "
          f"{row['maintenance2_s']}s folded in")
    return 0


def _post_state(wd: str) -> int:
    """Sections 4-5: encoded store intact through maintenance (device
    differential + compression ratio + baseline lineage), rollback
    restores pre-maintenance digests byte-identically."""
    from nds_tpu.columnar import delta
    from nds_tpu.io.snapshots import SnapshotLog
    from nds_tpu.nds import rollback
    from nds_tpu.nds.maintenance import MUTABLE_TABLES
    from nds_tpu.nds.power import SUITE
    from nds_tpu.utils.config import EngineConfig
    from nds_tpu.utils.power_core import run_query_stream

    wh = os.path.join(wd, "wh")
    stream0 = os.path.join(wd, "streams", "query_0.sql")

    # base files never rewritten: every mutated table's live lineage is
    # its baseline parts plus versioned delta segments
    import re
    vdir = re.compile(r"(?:^|[\\/])_v\d+[\\/]")
    log = SnapshotLog(wh)
    current = log.current(MUTABLE_TABLES)
    for t in MUTABLE_TABLES:
        rel = [os.path.relpath(p, wh) for p in current.get(t, [])]
        if not any(vdir.search(p) for p in rel):
            return _fail(f"{t}: no versioned delta files in lineage "
                         f"({rel})")
        if not delta.has_delta_paths(rel):
            return _fail(f"{t}: lineage lost its delta segments — "
                         f"maintenance must not rewrite the base")
        if not [p for p in rel if not vdir.search(p)]:
            return _fail(f"{t}: baseline part files dropped from "
                         f"lineage — base was rewritten")

    pre = _digests(os.path.join(wd, "reports", "json"))
    if not pre or any(d is None for d in pre.values()):
        return _fail(f"power phase journal has no result digests: {pre}")

    # device placement over the maintained warehouse (encoded store +
    # delta live-masks upload) vs a fresh CPU oracle
    runs = {}
    for tag, backend in (("dev", "tpu"), ("orc", "cpu")):
        jdir = os.path.join(wd, f"post_{tag}_json")
        cfg = EngineConfig(overrides={"engine.backend": backend,
                                      "columnar.encode": "auto"})
        failures = run_query_stream(
            SUITE, wh, stream0,
            os.path.join(wd, f"post_{tag}_time.csv"),
            config=cfg, json_summary_folder=jdir,
            output_prefix=os.path.join(wd, f"post_{tag}_out"))
        if failures:
            return _fail(f"post-maintenance {tag} run: {failures} "
                         f"queries failed")
        runs[tag] = _digests(jdir)
    # cross-backend diff is order-insensitive (under-specified ORDER BY
    # ties land differently per placement), exactly like the bench's
    # validate phase
    from nds_tpu.nds.validate import iterate_queries
    unmatched = iterate_queries(
        os.path.join(wd, "post_dev_out"),
        os.path.join(wd, "post_orc_out"), stream0,
        ignore_ordering=True, epsilon=0.00001)
    if unmatched:
        return _fail(f"post-maintenance device results diverge from "
                     f"the CPU oracle: {unmatched}")
    if runs["orc"] == pre:
        return _fail("maintenance was a no-op: post-maintenance "
                     "digests identical to pre-maintenance")
    ratios = {}
    jdir = os.path.join(wd, "post_dev_json")
    for name in os.listdir(jdir):
        if name.endswith("_queries.json"):
            continue
        with open(os.path.join(jdir, name)) as f:
            s = json.load(f)
        r = (s.get("engineTimings") or {}).get("compression_ratio")
        if r is not None:
            ratios[s.get("query", name)] = r
    if not ratios or min(ratios.values()) <= 1.0:
        return _fail(f"compression_ratio must stay > 1 through "
                     f"maintenance: {ratios}")
    print(f"OK: maintained warehouse — device digests == CPU oracle "
          f"on {len(runs['dev'])} queries, compression ratios "
          f"{min(ratios.values()):.2f}..{max(ratios.values()):.2f}")

    # rollback = manifest truncation; a power re-run must reproduce the
    # ORIGINAL power phase byte-for-byte
    rollback.rollback(wh, 0.0)
    rb_jdir = os.path.join(wd, "rb_json")
    failures = run_query_stream(
        SUITE, wh, stream0, os.path.join(wd, "rb_time.csv"),
        config=EngineConfig(overrides={"engine.backend": "cpu"}),
        json_summary_folder=rb_jdir)
    if failures:
        return _fail(f"post-rollback run: {failures} queries failed")
    rb = _digests(rb_jdir)
    if rb != pre:
        diff = {q for q in pre if rb.get(q) != pre[q]}
        return _fail(f"rollback did not restore pre-maintenance "
                     f"digests: {sorted(diff)}")
    print(f"OK: rollback restored all {len(pre)} pre-maintenance "
          f"query digests byte-identically")
    return 0


def _invalidation_scope() -> int:
    """Section 6: DML invalidation is table-scoped — an unrelated
    query's plan survives a mutation and re-runs with zero compiles."""
    from nds_tpu.datagen import tpcds
    from nds_tpu.engine.device_exec import make_device_factory
    from nds_tpu.engine.session import Session
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds.schema import get_schemas
    from nds_tpu.obs import metrics as obs_metrics

    schemas = get_schemas()
    sess = Session.for_nds(make_device_factory())
    for t in ("web_sales", "date_dim"):
        sess.register_table(
            from_arrays(t, schemas[t], tpcds.gen_table(t, SCALE)))

    q_dim = "select count(*) as c from date_dim where d_year = 2000"
    q_fact = "select count(*) as c from web_sales"
    dim0 = int(sess.sql(q_dim).cols[0][0])
    keys_dim = set(sess._plan_cache)
    fact0 = int(sess.sql(q_fact).cols[0][0])
    exp = int(sess.sql("select count(*) as c from web_sales "
                       "where ws_quantity > 95").cols[0][0])
    keys_fact = set(sess._plan_cache) - keys_dim
    sess.sql(q_dim), sess.sql(q_fact)  # warm

    sess.sql("insert into web_sales "
             "(select * from web_sales where ws_quantity > 95)")
    keys_after = set(sess._plan_cache)
    if not keys_dim <= keys_after:
        return _fail("DML to web_sales evicted the date_dim plan — "
                     "invalidation must scope to the mutated table")
    if keys_fact & keys_after:
        return _fail("DML to web_sales left stale web_sales plans "
                     "cached")

    snap = obs_metrics.snapshot()
    dim1 = int(sess.sql(q_dim).cols[0][0])
    compiles = obs_metrics.delta(snap, obs_metrics.snapshot())[
        "counters"].get("compiles_total", 0)
    if compiles:
        return _fail(f"unaffected query recompiled after unrelated "
                     f"DML: {compiles} compiles (want 0)")
    if dim1 != dim0:
        return _fail(f"unaffected query changed answer: {dim0} -> "
                     f"{dim1}")
    fact1 = int(sess.sql(q_fact).cols[0][0])
    if fact1 != fact0 + exp:
        return _fail(f"mutated-table query missed the insert: "
                     f"{fact0} + {exp} != {fact1}")
    print(f"OK: invalidation scoped — date_dim plan survived "
          f"(0 compiles on re-run), web_sales count {fact0} -> {fact1}")
    return 0


def main(argv=None) -> int:
    wd = tempfile.mkdtemp(prefix="maint_check_")
    try:
        rc = (_bench_kill_resume(wd) or _post_state(wd)
              or _invalidation_scope())
    finally:
        if os.environ.get("NDS_TPU_MAINT_KEEP"):
            print(f"keeping workdir {wd}")
        else:
            shutil.rmtree(wd, ignore_errors=True)
    if rc == 0:
        print("MAINT CHECK OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
