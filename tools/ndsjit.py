"""ndsjit: run the recompile & transfer hazard auditor over the tree.

Drives ``nds_tpu/analysis/jit_hazards.py`` (rule catalog NDSJ301-304;
NDSJ300 reports malformed/stale suppressions). The static half of the
pair whose runtime half is ``nds_tpu/analysis/jitsan.py`` — ndsjit
finds the hazard classes in source, jitsan witnesses them (or their
absence) on live dispatch windows. Configuration comes from
``[tool.ndsjit]`` in pyproject.toml (ndslint's shape):

    roots   = ["nds_tpu"]      # directories to audit
    exclude = []               # path substrings to skip
    rules   = []               # rule-id allowlist ([] = all)

Suppressions are per-line, shared grammar with ndslint/ndsraces:

    keep_np[s:e] = np.asarray(mask_d)  # ndsjit: waive[NDSJ303] -- sanctioned sync: the mask IS the product
    compiled(bufs, 0)                  # ndsjit: disable=NDSJ304

Exit 0 when the tree is clean (waived findings print with notes under
-v); exit 1 on any unwaived violation, malformed marker, or stale
marker. ``--jitsan-selftest`` runs a private jitsan sanitizer through
a real compile + guarded dispatch + hidden scalarization and exits 0
only when every leg is caught — the tier-1 proof the runtime detector
fires. Run by tools/static_checks.py as a tier-1 gate.
"""

from __future__ import annotations

import argparse
import os
import pathlib
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import ndslint  # noqa: E402

from nds_tpu.analysis import jit_hazards  # noqa: E402

DEFAULT_CONFIG = {
    "roots": ["nds_tpu"],
    "exclude": [],
    "rules": [],
}


def load_config(repo: pathlib.Path) -> dict:
    """[tool.ndsjit] from pyproject.toml, through ndslint's parser
    (one config grammar for all three gates)."""
    cfg = dict(DEFAULT_CONFIG)
    cfg.update(ndslint.load_section(repo, "tool.ndsjit"))
    return cfg


def run(repo: pathlib.Path, verbose: bool = False,
        cfg: "dict | None" = None) -> int:
    cfg = load_config(repo) if cfg is None else cfg
    sources = ndslint.collect_sources(repo, cfg)
    enabled = set(cfg["rules"]) or None
    res = jit_hazards.scan_sources(sources, enabled=enabled)
    for v in res.violations + res.errors:
        print(v)
    if verbose:
        for v in res.waived:
            print(f"{v.path}:{v.line}: {v.rule} waived -- "
                  f"{v.waiver_note}")
    bad = len(res.violations) + len(res.errors)
    print(f"{'FAIL' if bad else 'OK'}: {bad} violation(s), "
          f"{len(res.waived)} waived, {len(sources)} file(s)")
    return 1 if bad else 0


def jitsan_selftest() -> int:
    from nds_tpu.analysis import jitsan
    ok = jitsan.selftest()
    print(f"{'OK' if ok else 'FAIL'}: jitsan "
          f"{'caught' if ok else 'MISSED'} the seeded compile, "
          f"undeclared scalarization, and declared read-back")
    return 0 if ok else 1


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print waived findings with their notes")
    ap.add_argument("--jitsan-selftest", action="store_true",
                    help="run the runtime sanitizer against a seeded "
                         "compile + hidden transfer; exit 0 iff every "
                         "leg is caught")
    args = ap.parse_args(argv)
    repo = pathlib.Path(__file__).resolve().parent.parent
    if args.jitsan_selftest:
        return jitsan_selftest()
    return run(repo, verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
