"""Admin CLI for the persistent AOT plan cache (nds_tpu/cache/).

Verbs:

- ``ls``     — list every entry's manifest (kind, size, age, platform,
  jax version); pure filesystem, no jax import.
- ``verify`` — re-hash every payload against its sha256 manifest and
  report corrupt/unreadable entries (exit 1 when any fail).
- ``prune``  — delete entries by age (``--days``), by jax-version skew
  against the running jax (``--other-jax``), or failing verification
  (``--corrupt``).
- ``warm``   — compile every statement of a suite into a cold cache:
  build a session exactly like a power run (unified pipeline,
  ``--backend tpu|distributed|cpu``, ``--mesh N`` shards), register a
  warehouse (``--data_dir``, or in-memory datagen at ``--sf`` when
  omitted), and run all 125 statements so every compile persists. The
  next process pointed at the cache answers the whole workload with
  zero compiles.

Warming EXECUTES each statement rather than stopping at ``.compile()``:
staged plans register their sub-programs' result tables, whose content
feeds the main program's fingerprint — the only way to mint the exact
keys a real run will look up is to run the real pipeline. Results are
discarded; the compile side effects are the product.

Fingerprints fold in the backend platform and table content, so a warm
is only useful to runs on the SAME platform against the SAME warehouse:
warm on the TPU host for TPU runs (the acceptance sweep —
``--suite all`` on bare CPU with ``JAX_PLATFORMS=cpu`` — proves the
control plane needs no accelerator).

Usage:
  python tools/ndscache.py ls [--dir D]
  python tools/ndscache.py verify [--dir D]
  python tools/ndscache.py prune [--dir D] [--days N] [--other-jax] [--corrupt]
  python tools/ndscache.py warm [--dir D] [--suite nds|nds_h|all]
                                [--backend tpu|distributed|cpu]
                                [--mesh N] [--data_dir PATH] [--sf F]
                                [--input_format parquet|raw|...]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from nds_tpu import cache as plan_cache  # noqa: E402
from nds_tpu.cache.store import PlanCache  # noqa: E402


def _resolve_dir(args) -> str:
    d = args.dir or os.environ.get(plan_cache.ENV_DIR)
    if not d:
        print("error: no cache dir (--dir or NDS_TPU_PLAN_CACHE)")
        sys.exit(2)
    return d


def cmd_ls(args) -> int:
    store = PlanCache(_resolve_dir(args), readonly=True)
    entries = store.entries()
    if not entries:
        print("(empty cache)")
        return 0
    now = time.time()
    total = 0
    print(f"{'FINGERPRINT':16} {'KIND':22} {'SIZE':>10} {'AGE':>8} "
          f"{'PLATFORM':8} JAX")
    for m in entries:
        fp = m.get("fingerprint", "?")
        if m.get("unreadable"):
            print(f"{fp[:16]:16} <unreadable manifest>")
            continue
        size = m.get("size_bytes", 0)
        total += size
        age_h = (now - m.get("created_unix", now)) / 3600.0
        print(f"{fp[:16]:16} {str(m.get('kind', '?')):22} "
              f"{size:>10} {age_h:>7.1f}h "
              f"{str(m.get('platform', '?')):8} {m.get('jax', '?')}")
    print(f"{len(entries)} entr{'y' if len(entries) == 1 else 'ies'}, "
          f"{total} bytes")
    return 0


def cmd_verify(args) -> int:
    store = PlanCache(_resolve_dir(args), readonly=True)
    entries = store.entries()
    bad = store.verify()
    for fp in bad:
        print(f"CORRUPT: {fp}")
    print(f"{'FAIL' if bad else 'OK'}: {len(bad)} corrupt of "
          f"{len(entries)} entr{'y' if len(entries) == 1 else 'ies'}")
    return 1 if bad else 0


def cmd_prune(args) -> int:
    store = PlanCache(_resolve_dir(args))
    jax_version = None
    if args.other_jax:
        import jax
        jax_version = jax.__version__
    removed = store.prune(keep_days=args.days, jax_version=jax_version,
                          corrupt=args.corrupt)
    for fp in removed:
        print(f"pruned: {fp}")
    print(f"{len(removed)} entr{'y' if len(removed) == 1 else 'ies'} "
          f"removed")
    return 0


# ------------------------------------------------------------------ warm

def _gen_tables(suite_name: str, sf: float) -> dict:
    """In-memory warehouse at scale ``sf`` (no --data_dir): the same
    datagen the differential tests use."""
    from nds_tpu.io.host_table import from_arrays
    if suite_name == "nds_h":
        from nds_tpu.datagen import tpch as gen
        from nds_tpu.nds_h.schema import get_schemas
    else:
        from nds_tpu.datagen import tpcds as gen
        from nds_tpu.nds.schema import get_schemas
    schemas = get_schemas()
    return {t: from_arrays(t, schemas[t], gen.gen_table(t, sf))
            for t in schemas}


def _warm_suite(suite_name: str, args, config) -> tuple:
    """Run every statement of one suite through a power-run-equivalent
    session; returns (statements, failures list)."""
    from nds_tpu.utils import power_core
    if suite_name == "nds_h":
        from nds_tpu.nds_h import streams
        from nds_tpu.nds_h.power import SUITE
        units = [(f"q{qn}", list(streams.statements(qn)))
                 for qn in streams.stream_order(0)]
    else:
        from nds_tpu.nds import streams
        from nds_tpu.nds.power import SUITE
        units = []
        for qn in streams.available_templates():
            parts = [s for s in streams.render_query(qn).split(";")
                     if s.strip()]
            units.append((f"q{qn}", parts))
    session = power_core.make_session(SUITE, config)
    if args.data_dir:
        power_core.load_warehouse(
            SUITE, session, args.data_dir, args.input_format,
            schemas=power_core.suite_schemas(SUITE, config))
    else:
        for table in _gen_tables(suite_name, args.sf).values():
            session.register_table(table)
    n, failures = 0, []
    subset = set(args.queries or [])
    if subset:
        units = [(q, s) for q, s in units if q in subset]
    for qname, stmts in units:
        for i, stmt in enumerate(stmts, 1):
            label = (f"{suite_name} {qname}"
                     + (f" part{i}" if len(stmts) > 1 else ""))
            n += 1
            try:
                session.sql(stmt)
            except Exception as exc:  # noqa: BLE001 - keep sweeping
                failures.append(f"{label}: {type(exc).__name__}: {exc}")
            else:
                if args.verbose:
                    print(f"  warmed {label}")
    return n, failures


def cmd_warm(args) -> int:
    cache_dir = _resolve_dir(args)
    if args.mesh and args.backend != "distributed":
        print("error: --mesh requires --backend distributed")
        return 2
    if args.backend == "distributed" and args.mesh:
        # a CPU host needs virtual devices BEFORE jax initializes
        flags = os.environ.get("XLA_FLAGS", "")
        if ("xla_force_host_platform_device_count" not in flags
                and os.environ.get("JAX_PLATFORMS", "") == "cpu"):
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.mesh}").strip()
    from nds_tpu.obs import metrics as obs_metrics
    from nds_tpu.utils.config import EngineConfig
    overrides = {"engine.backend": args.backend,
                 "cache.dir": cache_dir}
    if args.mesh:
        overrides["engine.mesh.shards"] = args.mesh
    before = obs_metrics.snapshot()
    total, failures = 0, []
    for suite_name in (("nds", "nds_h") if args.suite == "all"
                       else (args.suite,)):
        config = EngineConfig(overrides=dict(overrides))
        n, fails = _warm_suite(suite_name, args, config)
        total += n
        failures.extend(fails)
    d = obs_metrics.delta(before, obs_metrics.snapshot()
                          ).get("counters", {})
    for line in failures:
        print(f"FAILED: {line}")
    print(f"{'FAIL' if failures else 'OK'}: warmed {total} statement(s) "
          f"({len(failures)} failed) into {cache_dir}: "
          f"compiles={int(d.get('compiles_total', 0))} "
          f"recompiles={int(d.get('recompiles_total', 0))} "
          f"hits={int(d.get('compile_cache_hits_total', 0))} "
          f"bytes_written="
          f"{int(d.get('compile_cache_bytes_written_total', 0))}")
    return 1 if failures else 0


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("ls", "verify", "prune", "warm"):
        p = sub.add_parser(name)
        p.add_argument("--dir", help="cache directory "
                                     "(default: NDS_TPU_PLAN_CACHE)")
        if name == "prune":
            p.add_argument("--days", type=float,
                           help="drop entries older than this many days")
            p.add_argument("--other-jax", action="store_true",
                           help="drop entries built by a jax other "
                                "than the one running")
            p.add_argument("--corrupt", action="store_true",
                           help="drop entries failing sha256 verify")
        if name == "warm":
            p.add_argument("--suite", choices=("nds", "nds_h", "all"),
                           default="all")
            p.add_argument("--backend",
                           choices=("tpu", "distributed", "cpu"),
                           default="tpu")
            p.add_argument("--mesh", type=int, default=0,
                           help="mesh shards (engine.mesh.shards) for "
                                "--backend distributed")
            p.add_argument("--data_dir",
                           help="warehouse to register (the warm is "
                                "only valid for runs against this "
                                "exact data)")
            p.add_argument("--input_format", default="parquet")
            p.add_argument("--sf", type=float, default=0.01,
                           help="in-memory datagen scale factor when "
                                "--data_dir is omitted")
            p.add_argument("--queries", nargs="+",
                           help="warm only these templates (e.g. q1 "
                                "q6); default: every statement")
            p.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    return {"ls": cmd_ls, "verify": cmd_verify,
            "prune": cmd_prune, "warm": cmd_warm}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
