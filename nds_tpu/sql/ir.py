"""Typed expression IR — what the executors actually evaluate.

The planner lowers parsed AST expressions into this IR with dtypes
resolved. Design choices are TPU-driven (SURVEY.md §7 "hard parts" #3):

- DECIMAL stays scaled int64 through +,-,* (exact, integer ALU path);
  division and AVG convert to float64 — TPC validation is epsilon-based
  (`nds/nds_validate.py:194-215`), so float division is within contract.
- Dates are epoch-day int32; EXTRACT lowers to integer civil-date math.
- String predicates never touch string data at run time: the planner binds
  them against the column dictionary (LIKE/substring/IN evaluate on the
  host dictionary once, producing code sets), so devices compare int32
  codes only. That binding happens in the engine layer; here LIKE et al.
  remain symbolic.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from nds_tpu.engine.types import (
    BOOL, DATE, DType, FLOAT64, INT32, INT64, DecimalType, IntType,
    FloatType, StringType, DateType, BoolType,
)


class IR:
    dtype: DType


@dataclass
class ColRef(IR):
    binding: str
    name: str
    dtype: DType = None

    def __repr__(self):
        return f"{self.binding}.{self.name}"


@dataclass
class Lit(IR):
    value: object      # python int (scaled for decimals) | str | None | bool
    dtype: DType = None

    def __repr__(self):
        return f"lit({self.value}:{self.dtype})"


@dataclass
class Arith(IR):
    op: str            # + - * / %
    left: IR
    right: IR
    dtype: DType = None


@dataclass
class Cmp(IR):
    op: str            # = <> < <= > >=
    left: IR
    right: IR
    dtype: DType = BOOL


@dataclass
class BoolOp(IR):
    op: str            # and | or
    args: list[IR] = field(default_factory=list)
    dtype: DType = BOOL


@dataclass
class Not(IR):
    operand: IR
    dtype: DType = BOOL


@dataclass
class Neg(IR):
    operand: IR
    dtype: DType = None


@dataclass
class CaseIR(IR):
    whens: list[tuple[IR, IR]] = field(default_factory=list)
    else_: Optional[IR] = None
    dtype: DType = None


@dataclass
class LikeIR(IR):
    operand: IR
    pattern: str
    negated: bool = False
    dtype: DType = BOOL


@dataclass
class InListIR(IR):
    operand: IR
    values: list[object] = field(default_factory=list)  # python values
    negated: bool = False
    dtype: DType = BOOL


@dataclass
class IsNullIR(IR):
    operand: IR
    negated: bool = False
    dtype: DType = BOOL


@dataclass
class ExtractIR(IR):
    part: str
    operand: IR
    dtype: DType = INT32


@dataclass
class SubstrIR(IR):
    operand: IR
    start: int
    length: Optional[int]
    dtype: DType = None


@dataclass
class StrMapIR(IR):
    """upper()/lower(): per-dictionary-entry string transform (device:
    codes untouched, dictionary rewritten + re-sorted)."""
    op: str              # upper | lower
    operand: IR
    dtype: DType = None


@dataclass
class ConcatIR(IR):
    """String concatenation with a LITERAL prefix/suffix (q5's
    'store' || s_store_id ids). Restricted to literal ⊕ column so the
    device engine can implement it as a dictionary transform (codes
    untouched, only the host-side dictionary rewritten)."""
    prefix: str
    operand: IR          # string-typed column expression
    suffix: str
    dtype: DType = None


@dataclass
class CastIR(IR):
    operand: IR
    dtype: DType = None


@dataclass
class AggRef(IR):
    """Reference to aggregate #index of the enclosing Aggregate node."""
    index: int
    dtype: DType = None

    def __repr__(self):
        return f"agg#{self.index}"


@dataclass
class ScalarRef(IR):
    """Result of an uncorrelated scalar subquery, planned separately and
    bound at execution time (plan_id indexes LogicalPlan.scalar_subplans)."""
    plan_id: int
    dtype: DType = None

    def __repr__(self):
        return f"scalar#{self.plan_id}"


@dataclass
class ParamRef(IR):
    """A hoisted query literal (sql/params.py): the VALUE lives in
    ``PlannedQuery.param_values[index]`` (a plain attribute, invisible
    to the plan fingerprint), so same-template queries with different
    literals share one canonical plan — and one compiled program, with
    the literal supplied as a runtime scalar input."""
    index: int
    dtype: DType = None

    def __repr__(self):
        return f"param#{self.index}:{self.dtype}"


@dataclass
class DictParamIR(IR):
    """A hoisted STRING predicate over a dictionary-encoded scan
    column: LIKE pattern, comparison literal, or IN-list, with the
    literal(s) in ``param_values[index]``. The device program takes a
    boolean membership table over the operand's dictionary as a runtime
    input; sql/params.bind_params computes that table on the host per
    request (like_mask / lexicographic compare / isin over the derived
    dictionary). ``table``/``column`` name the base scan column whose
    dictionary the operand's transform chain starts from."""
    operand: IR = None       # ColRef chain (Substr/StrMap/Concat ok)
    table: str = ""
    column: str = ""
    kind: str = "cmp"        # like | cmp | inlist
    op: str = "="            # comparison op (kind == "cmp")
    index: int = 0
    negated: bool = False
    # binder-side transform spec: the operand's string-transform chain
    # RESOLVED through derived-table aliases down to the base scan
    # column, innermost-first, as opaque tuples (("substr", start,
    # length) | ("map", op) | ("concat", prefix, suffix)) —
    # sql/params.derive_dictionary replays it on the host dictionary.
    # A spec, not IR: nothing evaluates it in any row namespace.
    chain: tuple = ()
    dtype: DType = BOOL

    def __repr__(self):
        return (f"dictparam#{self.index}:{self.kind}"
                f"[{self.table}.{self.column}]")


@dataclass
class InListParamIR(IR):
    """A hoisted NUMERIC/date IN-list: ``param_values[index]`` holds the
    value tuple; the device program takes a fixed-width vector input
    (``width`` is part of the plan, so variants with equal list lengths
    share a program)."""
    operand: IR = None
    index: int = 0
    width: int = 0
    negated: bool = False
    dtype: DType = BOOL

    def __repr__(self):
        return f"inparam#{self.index}x{self.width}"


@dataclass
class WindowRef(IR):
    """Reference to window column #index of the enclosing Window node."""
    index: int
    dtype: DType = None

    def __repr__(self):
        return f"win#{self.index}"


@dataclass
class GroupingRef(IR):
    """grouping(<key>) marker: 0 when the key participates in the row's
    grouping set, 1 when rolled up (NULL-filled). Resolved per grouping-
    set branch to a constant column (key_index = index into the select's
    group_by list)."""
    key_index: int
    dtype: DType = INT32

    def __repr__(self):
        return f"grouping#{self.key_index}"


def is_decimal(t: DType) -> bool:
    return isinstance(t, DecimalType)


def common_scale(a: DType, b: DType) -> int:
    sa = a.scale if is_decimal(a) else 0
    sb = b.scale if is_decimal(b) else 0
    return max(sa, sb)


def arith_type(op: str, lt: DType, rt: DType) -> DType:
    """Result dtype of an arithmetic op, per the decimal policy above."""
    if isinstance(lt, DateType) or isinstance(rt, DateType):
        return DATE  # date +/- days
    if op == "/":
        return FLOAT64
    if isinstance(lt, FloatType) or isinstance(rt, FloatType):
        return FLOAT64
    if is_decimal(lt) or is_decimal(rt):
        if op == "*":
            return DecimalType(38, (lt.scale if is_decimal(lt) else 0)
                               + (rt.scale if is_decimal(rt) else 0))
        return DecimalType(38, common_scale(lt, rt))
    if isinstance(lt, IntType) and isinstance(rt, IntType):
        return INT64 if max(lt.bits, rt.bits) > 32 else INT32
    raise TypeError(f"cannot apply {op} to {lt} and {rt}")


def agg_type(func: str, arg_t: DType | None) -> DType:
    if func == "count":
        return INT64
    if func in ("avg", "stddev_samp", "stddev"):
        return FLOAT64
    if func in ("sum", "min", "max"):
        if arg_t is None:
            raise TypeError(f"{func} requires an argument type")
        if isinstance(arg_t, IntType):
            return INT64 if func == "sum" else arg_t
        return arg_t
    raise TypeError(f"unknown aggregate {func}")


def walk(e: IR):
    """Yield e and all IR descendants."""
    yield e
    for f in vars(e).values():
        if isinstance(f, IR):
            yield from walk(f)
        elif isinstance(f, list):
            for x in f:
                if isinstance(x, IR):
                    yield from walk(x)
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, IR):
                            yield from walk(y)
