"""Recursive-descent SQL parser for the TPC dialect subset.

Grammar coverage is driven by the benchmark queries (see ast.py). The
Spark-dialect quirks the reference bakes into its template patches
(`nds/tpcds-gen/patches/templates.patch`: `+ interval N days`, backtick
aliases; `nds-h/tpch-gen/patches/template.patch`: plain `;` termination)
are accepted natively here.
"""

from __future__ import annotations

from nds_tpu.sql import ast
from nds_tpu.sql.lexer import Token, tokenize

_KEYWORDS_NONIDENT = {
    "select", "from", "where", "group", "order", "by", "having", "limit",
    "union", "intersect", "except", "join", "inner", "left", "right", "full",
    "outer", "cross", "on", "as", "and", "or", "not", "in", "exists",
    "between", "like", "is", "null", "case", "when", "then", "else", "end",
    "distinct", "asc", "desc", "with",
}


class ParseError(ValueError):
    def __init__(self, msg: str, tok: Token | None = None):
        if tok is not None:
            msg = f"{msg} (at {tok.pos}: {tok.value!r})"
        super().__init__(msg)


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # --- token helpers -----------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.i + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.value.lower() in kws

    def take_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.take_kw(kw):
            raise ParseError(f"expected {kw.upper()}", self.peek())

    def at_punct(self, p: str) -> bool:
        t = self.peek()
        return t.kind in ("punct", "op") and t.value == p

    def take_punct(self, p: str) -> bool:
        if self.at_punct(p):
            self.next()
            return True
        return False

    def expect_punct(self, p: str) -> None:
        if not self.take_punct(p):
            raise ParseError(f"expected {p!r}", self.peek())

    # --- entry -------------------------------------------------------------

    def parse_statement(self):
        if self.at_kw("create"):
            self.next()
            self.take_kw("temp") or self.take_kw("temporary")
            self.expect_kw("view")
            name = self.next().value.lower()
            columns: list[str] = []
            if self.take_punct("("):
                while True:
                    columns.append(self.next().value.lower())
                    if not self.take_punct(","):
                        break
                self.expect_punct(")")
            self.expect_kw("as")
            q = self._parse_query()
            self.take_punct(";")
            return ast.CreateView(name, columns, q)
        if self.at_kw("drop"):
            self.next()
            self.expect_kw("view")
            if_exists = False
            if self.take_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            name = self.next().value.lower()
            self.take_punct(";")
            return ast.DropView(name, if_exists)
        if self.at_kw("insert"):
            self.next()
            self.expect_kw("into")
            name = self.next().value.lower()
            wrapped = self.take_punct("(")
            q = self._parse_query()
            if wrapped:
                self.expect_punct(")")
            self.take_punct(";")
            t = self.peek()
            if t.kind != "eof":
                raise ParseError("trailing tokens after INSERT", t)
            return ast.Insert(name, q)
        if self.at_kw("delete"):
            self.next()
            self.expect_kw("from")
            name = self.next().value.lower()
            where = None
            if self.take_kw("where"):
                where = self.parse_expr()
            self.take_punct(";")
            t = self.peek()
            if t.kind != "eof":
                raise ParseError("trailing tokens after DELETE", t)
            return ast.Delete(name, where)
        sel = self._parse_query()
        self.take_punct(";")
        t = self.peek()
        if t.kind != "eof":
            raise ParseError("trailing tokens after statement", t)
        return sel

    def _parse_query(self) -> ast.Select:
        """[WITH ctes] select — the query body shared by top-level
        statements, CREATE VIEW ... AS, and INSERT INTO ... (query)."""
        ctes: dict[str, ast.Select] = {}
        if self.take_kw("with"):
            while True:
                name = self.next().value
                self.expect_kw("as")
                self.expect_punct("(")
                ctes[name.lower()] = self.parse_select()
                self.expect_punct(")")
                if not self.take_punct(","):
                    break
        sel = self.parse_select()
        sel.ctes.update(ctes)
        return sel

    def parse_select(self) -> ast.Select:
        sel = self._parse_simple_select()
        # set operations bind left-to-right
        while self.at_kw("union", "intersect", "except"):
            op = self.next().value.lower()
            if op == "union" and self.take_kw("all"):
                op = "union all"
            elif self.take_kw("distinct"):
                pass  # distinct is the default semantics
            rhs = self._parse_simple_select()
            # a trailing ORDER BY / LIMIT binds to the whole set operation,
            # not the last branch — hoist it out of the rhs
            if rhs.order_by or rhs.limit is not None:
                sel.order_by, rhs.order_by = rhs.order_by, []
                sel.limit, rhs.limit = rhs.limit, None
            sel.set_ops.append((op, rhs))
        # ORDER BY / LIMIT after a set operation applies to the whole result
        if self.at_kw("order"):
            self._parse_order_limit(sel)
        return sel

    def _parse_simple_select(self) -> ast.Select:
        if self.take_punct("("):
            sel = self.parse_select()
            self.expect_punct(")")
            return sel
        self.expect_kw("select")
        sel = ast.Select()
        sel.distinct = bool(self.take_kw("distinct"))
        self.take_kw("all")
        # select list
        while True:
            sel.items.append(self._parse_select_item())
            if not self.take_punct(","):
                break
        if self.take_kw("from"):
            sel.from_tables.append(self._parse_table_factor())
            while True:
                if self.take_punct(","):
                    sel.from_tables.append(self._parse_table_factor())
                    continue
                join_kind = self._maybe_join_kind()
                if join_kind is None:
                    break
                table = self._parse_table_factor()
                on = None
                if self.take_kw("on"):
                    on = self.parse_expr()
                sel.joins.append(ast.JoinClause(join_kind, table, on))
        if self.take_kw("where"):
            sel.where = self.parse_expr()
        if self.at_kw("group"):
            self.next()
            self.expect_kw("by")
            self._parse_group_by(sel)
        if self.take_kw("having"):
            sel.having = self.parse_expr()
        self._parse_order_limit(sel)
        return sel

    def _parse_group_by(self, sel: ast.Select) -> None:
        """Plain list, ROLLUP(...), CUBE(...), or GROUPING SETS((..),..).
        All lower to sel.group_by (the full key list) + sel.grouping_sets
        (index lists), matching the Spark dialect the reference's patched
        templates use (`nds/tpcds-gen/patches/templates.patch`)."""
        if self.at_kw("rollup") or self.at_kw("cube"):
            kind = self.next().value.lower()
            self.expect_punct("(")
            keys = [self.parse_expr()]
            while self.take_punct(","):
                keys.append(self.parse_expr())
            self.expect_punct(")")
            sel.group_by = keys
            n = len(keys)
            if kind == "rollup":
                sel.grouping_sets = [list(range(k))
                                     for k in range(n, -1, -1)]
            else:  # cube: all subsets, spec enumeration order
                sel.grouping_sets = [
                    [i for i in range(n) if mask & (1 << i)]
                    for mask in range((1 << n) - 1, -1, -1)]
            return
        if self.at_kw("grouping"):
            save = self.i
            self.next()
            if not self.take_kw("sets"):
                self.i = save
            else:
                self.expect_punct("(")
                keys: list = []
                key_index: dict = {}
                sets: list[list[int]] = []
                while True:
                    self.expect_punct("(")
                    one: list[int] = []
                    if not self.at_punct(")"):
                        while True:
                            e = self.parse_expr()
                            r = repr(e)
                            if r not in key_index:
                                key_index[r] = len(keys)
                                keys.append(e)
                            one.append(key_index[r])
                            if not self.take_punct(","):
                                break
                    self.expect_punct(")")
                    sets.append(one)
                    if not self.take_punct(","):
                        break
                self.expect_punct(")")
                sel.group_by = keys
                sel.grouping_sets = sets
                return
        while True:
            sel.group_by.append(self.parse_expr())
            if not self.take_punct(","):
                break

    def _parse_order_limit(self, sel: ast.Select) -> None:
        if self.at_kw("order"):
            self.next()
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                asc = True
                if self.take_kw("desc"):
                    asc = False
                else:
                    self.take_kw("asc")
                nulls_first = None
                if self.take_kw("nulls"):
                    nulls_first = bool(self.take_kw("first"))
                    if nulls_first is False:
                        self.expect_kw("last")
                sel.order_by.append(ast.OrderItem(e, asc, nulls_first))
                if not self.take_punct(","):
                    break
        if self.take_kw("limit"):
            t = self.next()
            if t.kind != "number":
                raise ParseError("expected LIMIT count", t)
            sel.limit = int(t.value)

    def _maybe_join_kind(self) -> str | None:
        if self.at_kw("join"):
            self.next()
            return "inner"
        for kw, kind in (("inner", "inner"), ("left", "left"),
                         ("right", "right"), ("full", "full"),
                         ("cross", "cross")):
            if self.at_kw(kw):
                save = self.i
                self.next()
                self.take_kw("outer")
                if self.take_kw("join"):
                    return kind
                self.i = save
                return None
        return None

    def _parse_table_factor(self):
        if self.take_punct("("):
            sub = self.parse_select()
            self.expect_punct(")")
            self.take_kw("as")
            alias_t = self.next()
            if alias_t.kind != "ident":
                raise ParseError("derived table requires an alias", alias_t)
            return ast.SubqueryRef(sub, alias_t.value.lower())
        t = self.next()
        if t.kind != "ident":
            raise ParseError("expected table name", t)
        name = t.value.lower()
        alias = None
        if self.take_kw("as"):
            alias = self.next().value.lower()
        elif (self.peek().kind == "ident"
              and self.peek().value.lower() not in _KEYWORDS_NONIDENT):
            alias = self.next().value.lower()
        return ast.TableRef(name, alias)

    def _parse_select_item(self) -> ast.SelectItem:
        if self.at_punct("*"):
            self.next()
            return ast.SelectItem(ast.Star())
        # table.* form
        if (self.peek().kind == "ident" and self.peek(1).value == "."
                and self.peek(2).value == "*"):
            table = self.next().value.lower()
            self.next()
            self.next()
            return ast.SelectItem(ast.Star(table))
        e = self.parse_expr()
        alias = None
        if self.take_kw("as"):
            alias = self.next().value.lower()
        elif (self.peek().kind == "ident"
              and self.peek().value.lower() not in _KEYWORDS_NONIDENT):
            alias = self.next().value.lower()
        return ast.SelectItem(e, alias)

    # --- expressions -------------------------------------------------------

    def parse_expr(self) -> ast.Expr:
        return self._parse_or()

    def _parse_or(self) -> ast.Expr:
        left = self._parse_and()
        while self.take_kw("or"):
            left = ast.BinOp("or", left, self._parse_and())
        return left

    def _parse_and(self) -> ast.Expr:
        left = self._parse_not()
        while self.take_kw("and"):
            left = ast.BinOp("and", left, self._parse_not())
        return left

    def _parse_not(self) -> ast.Expr:
        if self.take_kw("not"):
            return ast.UnaryOp("not", self._parse_not())
        return self._parse_comparison()

    def _parse_comparison(self) -> ast.Expr:
        left = self._parse_additive()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("=", "<>", "!=", "<", "<=", ">", ">="):
                self.next()
                op = "<>" if t.value == "!=" else t.value
                left = ast.BinOp(op, left, self._parse_additive())
                continue
            negated = False
            save = self.i
            if self.take_kw("not"):
                negated = True
            if self.take_kw("between"):
                low = self._parse_additive()
                self.expect_kw("and")
                high = self._parse_additive()
                left = ast.Between(left, low, high, negated)
                continue
            if self.take_kw("in"):
                self.expect_punct("(")
                if self.at_kw("select", "with"):
                    sub = self.parse_select()
                    self.expect_punct(")")
                    left = ast.InSubquery(left, sub, negated)
                else:
                    items = [self.parse_expr()]
                    while self.take_punct(","):
                        items.append(self.parse_expr())
                    self.expect_punct(")")
                    left = ast.InList(left, items, negated)
                continue
            if self.take_kw("like"):
                t = self.next()
                if t.kind != "string":
                    raise ParseError("LIKE requires a string pattern", t)
                left = ast.Like(left, t.value, negated)
                continue
            if negated:
                self.i = save  # NOT belonged to something else
                break
            if self.take_kw("is"):
                neg = bool(self.take_kw("not"))
                self.expect_kw("null")
                left = ast.IsNull(left, neg)
                continue
            break
        return left

    def _parse_additive(self) -> ast.Expr:
        left = self._parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value == "||":
                self.next()
                left = ast.FuncCall(
                    "concat", [left, self._parse_multiplicative()])
                continue
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                left = ast.BinOp(t.value, left, self._parse_multiplicative())
            else:
                return left

    def _parse_multiplicative(self) -> ast.Expr:
        left = self._parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                left = ast.BinOp(t.value, left, self._parse_unary())
            else:
                return left

    def _parse_unary(self) -> ast.Expr:
        t = self.peek()
        if t.kind == "op" and t.value == "-":
            self.next()
            return ast.UnaryOp("-", self._parse_unary())
        if t.kind == "op" and t.value == "+":
            self.next()
            return self._parse_unary()
        return self._parse_primary()

    def _parse_primary(self) -> ast.Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            if "." in t.value:
                return ast.Literal(t.value, "decimal")
            return ast.Literal(int(t.value), "int")
        if t.kind == "string":
            self.next()
            return ast.Literal(t.value, "string")
        if self.take_punct("("):
            if self.at_kw("select", "with"):
                sub = self.parse_select()
                self.expect_punct(")")
                return ast.ScalarSubquery(sub)
            e = self.parse_expr()
            self.expect_punct(")")
            return e
        if t.kind != "ident":
            raise ParseError("unexpected token in expression", t)
        word = t.value.lower()
        if word == "case":
            return self._parse_case()
        if word == "exists":
            self.next()
            self.expect_punct("(")
            sub = self.parse_select()
            self.expect_punct(")")
            return ast.Exists(sub)
        if word == "null":
            self.next()
            return ast.Literal(None, "null")
        if word == "date" and self.peek(1).kind == "string":
            self.next()
            return ast.Literal(self.next().value, "date")
        if word == "interval":
            self.next()
            amt_t = self.next()
            if amt_t.kind == "string":
                amount = int(amt_t.value)
            elif amt_t.kind == "number":
                amount = int(amt_t.value)
            else:
                raise ParseError("expected interval amount", amt_t)
            unit_t = self.next()
            unit = unit_t.value.lower().rstrip("s")
            if unit not in ("day", "month", "year"):
                raise ParseError(f"unsupported interval unit {unit!r}", unit_t)
            return ast.Interval(amount, unit)
        if word == "extract":
            self.next()
            self.expect_punct("(")
            part = self.next().value.lower()
            self.expect_kw("from")
            operand = self.parse_expr()
            self.expect_punct(")")
            return ast.Extract(part, operand)
        if word == "substring" or word == "substr":
            self.next()
            self.expect_punct("(")
            operand = self.parse_expr()
            if self.take_kw("from"):
                start = self.parse_expr()
                length = None
                if self.take_kw("for"):
                    length = self.parse_expr()
            else:
                self.expect_punct(",")
                start = self.parse_expr()
                length = None
                if self.take_punct(","):
                    length = self.parse_expr()
            self.expect_punct(")")
            return ast.Substring(operand, start, length)
        if word == "cast":
            self.next()
            self.expect_punct("(")
            operand = self.parse_expr()
            self.expect_kw("as")
            type_name = self.next().value.lower()
            if self.take_punct("("):  # e.g. decimal(12,2)
                while not self.take_punct(")"):
                    self.next()
            self.expect_punct(")")
            return ast.Cast(operand, type_name)
        if word in _KEYWORDS_NONIDENT:
            raise ParseError("unexpected keyword in expression", t)
        # function call or column reference
        if self.peek(1).value == "(" and self.peek(1).kind == "punct":
            name = self.next().value.lower()
            self.next()  # (
            if self.take_punct("*"):
                self.expect_punct(")")
                return self._maybe_window(ast.FuncCall(name, star=True))
            if self.take_punct(")"):
                return self._maybe_window(ast.FuncCall(name))
            distinct = bool(self.take_kw("distinct"))
            args = [self.parse_expr()]
            while self.take_punct(","):
                args.append(self.parse_expr())
            self.expect_punct(")")
            return self._maybe_window(
                ast.FuncCall(name, args, distinct))
        # column, possibly qualified
        name = self.next().value.lower()
        if self.at_punct(".") and self.peek(1).kind == "ident":
            self.next()
            col = self.next().value.lower()
            return ast.Column(col, name)
        return ast.Column(name)

    def _maybe_window(self, fc: ast.FuncCall) -> ast.Expr:
        """fc [OVER (PARTITION BY ... ORDER BY ... [ROWS ...])]."""
        if not self.at_kw("over"):
            return fc
        if fc.distinct:
            raise ParseError(
                f"DISTINCT window aggregate {fc.name} is unsupported")
        self.next()
        self.expect_punct("(")
        partition: list[ast.Expr] = []
        order: list[ast.OrderItem] = []
        frame = None
        if self.take_kw("partition"):
            self.expect_kw("by")
            partition.append(self.parse_expr())
            while self.take_punct(","):
                partition.append(self.parse_expr())
        if self.at_kw("order"):
            self.next()
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                asc = True
                if self.take_kw("desc"):
                    asc = False
                else:
                    self.take_kw("asc")
                nulls_first = None
                if self.take_kw("nulls"):
                    nulls_first = bool(self.take_kw("first"))
                    if nulls_first is False:
                        self.expect_kw("last")
                order.append(ast.OrderItem(e, asc, nulls_first))
                if not self.take_punct(","):
                    break
        if self.take_kw("rows"):
            # the workload's only frame: running aggregate (q51)
            self.expect_kw("between")
            self.expect_kw("unbounded")
            self.expect_kw("preceding")
            self.expect_kw("and")
            self.expect_kw("current")
            self.expect_kw("row")
            frame = "cum"
        self.expect_punct(")")
        return ast.WindowFunc(fc.name, [] if fc.star else fc.args,
                              partition, order, frame)

    def _parse_case(self) -> ast.Expr:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        whens: list[tuple[ast.Expr, ast.Expr]] = []
        while self.take_kw("when"):
            cond = self.parse_expr()
            if operand is not None:
                cond = ast.BinOp("=", operand, cond)
            self.expect_kw("then")
            whens.append((cond, self.parse_expr()))
        else_ = None
        if self.take_kw("else"):
            else_ = self.parse_expr()
        self.expect_kw("end")
        return ast.CaseWhen(whens, else_)


def parse(sql: str) -> ast.Select:
    return Parser(sql).parse_statement()
