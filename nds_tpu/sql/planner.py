"""AST -> logical plan: name resolution, subquery decorrelation, join
ordering, aggregate planning.

The reference leans on Spark Catalyst for all of this; here it is explicit
and tuned to the decision-support shape (SURVEY.md §7): closed-world
queries, star-schema joins, correlated subqueries of the classic TPC
patterns. Decorrelation rules:

- EXISTS / NOT EXISTS     -> SemiJoin/AntiJoin on extracted equi-pairs,
                             other correlated predicates become the join
                             residual (q4, q21, q22)
- expr IN (subquery)      -> SemiJoin on (expr = subquery column) (q18,
                             q20); NOT IN -> anti (q16)
- cmp with correlated
  scalar agg subquery     -> inner Aggregate grouped by correlation keys,
                             joined into the outer join graph; the
                             comparison becomes an ordinary predicate
                             (q2, q17, q20)
- uncorrelated scalar     -> planned separately, bound as ScalarRef at
                             execution (q11, q15, q22)

Common-conjunct hoisting across OR branches recovers the join key from
q19's disjunctive form.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field

from nds_tpu.engine.types import (
    BOOL, DATE, FLOAT64, INT32, INT64, DType, DecimalType, FloatType,
    IntType, Schema, StringType, DateType,
)
from nds_tpu.sql import ast, ir
from nds_tpu.sql import plan as P

AGG_FUNCS = {"sum", "avg", "min", "max", "count", "stddev_samp",
             "stddev"}
WINDOW_RANK_FUNCS = {"rank", "dense_rank", "row_number"}

_EPOCH = datetime.date(1970, 1, 1)


DUP_MARK = "#dup"  # internal suffix disambiguating repeated output names


def _dedupe_out_names(pairs: list) -> list:
    """Projection output names must be unique: executor contexts key
    columns by (binding, name), so q64's unaliased `cs1.syear ...
    cs2.syear` select list would silently collapse both outputs onto
    whichever column lands last. Internal names get a #dup suffix ('#'
    cannot appear in a SQL identifier); result display names strip it
    (`_display_name`), keeping the positional ResultTable contract."""
    seen: dict = {}
    out = []
    for n, e in pairs:
        c = seen.get(n, 0)
        seen[n] = c + 1
        out.append((n if c == 0 else f"{n}{DUP_MARK}{c}", e))
    return out


def _display_name(n: str) -> str:
    return n.split(DUP_MARK)[0]


class PlanError(ValueError):
    pass


@dataclass
class CatalogInfo:
    """Schemas plus the planner statistics (PKs for join-strategy choice,
    relative sizes for greedy join ordering)."""
    schemas: dict                      # table -> Schema
    primary_keys: dict = field(default_factory=dict)
    sizes: dict = field(default_factory=dict)   # table -> relative row weight

    def has_table(self, name: str) -> bool:
        return name in self.schemas


def _date_to_days(iso: str) -> int:
    y, m, d = (int(x) for x in iso.split("-"))
    return (datetime.date(y, m, d) - _EPOCH).days


def _add_months(days: int, months: int) -> int:
    d = _EPOCH + datetime.timedelta(days=days)
    total = d.year * 12 + (d.month - 1) + months
    y, m = divmod(total, 12)
    # TPC dates are always day-of-month-safe (day 1 or mid-month)
    return (datetime.date(y, m + 1, d.day) - _EPOCH).days


@dataclass
class Relation:
    binding: str
    node: P.Node
    columns: dict            # name -> DType
    size: float = 1.0        # selectivity-discounted (join ordering)
    unique_on: tuple = ()    # column names this relation is unique on
    phys_size: float = None  # undiscounted row capacity (probe choice)

    def __post_init__(self):
        if self.phys_size is None:
            self.phys_size = self.size


class Scope:
    """One select's name-resolution scope, chained to outer scopes."""

    def __init__(self, parent: "Scope | None" = None):
        self.parent = parent
        self.relations: dict[str, Relation] = {}

    def add(self, rel: Relation):
        if rel.binding in self.relations:
            raise PlanError(f"duplicate binding {rel.binding!r}")
        self.relations[rel.binding] = rel

    def resolve(self, col: ast.Column):
        """-> (ColRef, depth) where depth 0 = local, >0 = correlated."""
        depth = 0
        scope = self
        while scope is not None:
            if col.table:
                rel = scope.relations.get(col.table)
                if rel is not None and col.name in rel.columns:
                    return ir.ColRef(rel.binding, col.name,
                                     rel.columns[col.name]), depth
            else:
                hits = [r for r in scope.relations.values()
                        if col.name in r.columns]
                if len(hits) > 1:
                    raise PlanError(f"ambiguous column {col.name!r}")
                if hits:
                    r = hits[0]
                    return ir.ColRef(r.binding, col.name,
                                     r.columns[col.name]), depth
            scope = scope.parent
            depth += 1
        raise PlanError(f"cannot resolve column {col!r}")


def _flatten_and(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.BinOp) and e.op == "and":
        return _flatten_and(e.left) + _flatten_and(e.right)
    return [e]


def _flatten_or(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.BinOp) and e.op == "or":
        return _flatten_or(e.left) + _flatten_or(e.right)
    return [e]


def _hoist_common_disjuncts(conjuncts: list[ast.Expr]) -> list[ast.Expr]:
    """(A and X) or (A and Y) -> A and (X or Y). Recovers q19's join key."""
    out: list[ast.Expr] = []
    for c in conjuncts:
        branches = _flatten_or(c)
        if len(branches) < 2:
            out.append(c)
            continue
        branch_sets = [_flatten_and(b) for b in branches]
        common_reprs = set(repr(x) for x in branch_sets[0])
        for bs in branch_sets[1:]:
            common_reprs &= set(repr(x) for x in bs)
        if not common_reprs:
            out.append(c)
            continue
        for x in branch_sets[0]:
            if repr(x) in common_reprs:
                out.append(x)
        rests = []
        for bs in branch_sets:
            rest = [x for x in bs if repr(x) not in common_reprs]
            if not rest:
                rests = []
                break
            acc = rest[0]
            for x in rest[1:]:
                acc = ast.BinOp("and", acc, x)
            rests.append(acc)
        if rests:
            acc = rests[0]
            for x in rests[1:]:
                acc = ast.BinOp("or", acc, x)
            out.append(acc)
    return out


class Planner:
    def __init__(self, catalog: CatalogInfo, views: dict | None = None,
                 parameterize: bool = False):
        self.catalog = catalog
        self.views = views if views is not None else {}
        self.scalar_subplans: list[P.Node] = []
        self._binding_counter = 0
        self._views_stack: list[dict] = [{}]
        # hoist query literals into runtime parameters (sql/params.py):
        # same-template literal variants then share ONE canonical plan,
        # one AOT fingerprint, and one compiled program — the serving
        # layer's zero-compile-per-request contract
        self.parameterize = parameterize

    # ---------------------------------------------------------------- API

    def plan_statement(self, stmt) -> "P.PlannedQuery | tuple":
        """Select -> PlannedQuery; CreateView/DropView -> ('view', ...) action
        the session applies (q15 flow, `nds-h/nds_h_power.py:78-82`)."""
        from nds_tpu.obs import metrics as obs_metrics
        from nds_tpu.obs.trace import get_tracer
        obs_metrics.counter("plans_total").inc()
        with get_tracer().span("sql.plan", stmt=type(stmt).__name__):
            return self._plan_statement(stmt)

    def _plan_statement(self, stmt) -> "P.PlannedQuery | tuple":
        if isinstance(stmt, ast.CreateView):
            q = self.plan_select(stmt.query, None, {})
            node = q if isinstance(q, P.Node) else q
            if stmt.columns:
                node = self._rename_outputs(node, stmt.columns)
            return ("create_view", stmt.name, node)
        if isinstance(stmt, ast.DropView):
            return ("drop_view", stmt.name,
                    "if_exists" if stmt.if_exists else None)
        if isinstance(stmt, ast.Insert):
            if not self.catalog.has_table(stmt.table):
                raise PlanError(f"unknown insert target {stmt.table!r}")
            root = self.plan_select(stmt.query, None, {})
            target = self.catalog.schemas[stmt.table]
            if len(root.output) != len(target.fields):
                raise PlanError(
                    f"INSERT into {stmt.table}: select produces "
                    f"{len(root.output)} columns, table has "
                    f"{len(target.fields)}")
            names = [_display_name(n) for n, _ in root.output]
            return ("insert", stmt.table, self._annotated(
                P.PlannedQuery(root, self.scalar_subplans, names)))
        if isinstance(stmt, ast.Delete):
            if not self.catalog.has_table(stmt.table):
                raise PlanError(f"unknown delete target {stmt.table!r}")
            return ("delete", stmt.table, stmt.where)
        root = self.plan_select(stmt, None, {})
        names = [_display_name(n) for n, _ in root.output]
        planned = self._annotated(
            P.PlannedQuery(root, self.scalar_subplans, names))
        if self.parameterize:
            from nds_tpu.sql import params as sqlparams
            planned = sqlparams.parameterize(planned, self.catalog)
        return planned

    def _annotated(self, planned: P.PlannedQuery) -> P.PlannedQuery:
        """Stamp per-node kernel choices (engine/kernels.py) from the
        catalog's size statistics — the same stats the greedy join
        ordering and the scheduler cost model read. The choice lives on
        the plan nodes, so the AOT fingerprint distinguishes it and the
        executors never re-decide per trace."""
        from nds_tpu.engine import kernels
        kernels.annotate(planned, catalog=self.catalog)
        return planned

    # ----------------------------------------------------------- helpers

    def _fresh(self, prefix: str) -> str:
        self._binding_counter += 1
        return f"_{prefix}{self._binding_counter}"

    def _rename_outputs(self, node: P.Node, names: list[str]) -> P.Node:
        out = node.output
        if len(names) != len(out):
            raise PlanError("view column list length mismatch")
        b = self._fresh("v")
        exprs = [(new, ir.ColRef(node.binding, old, t))
                 for new, (old, t) in zip(names, out)]
        return P.Project(node, exprs, b)

    def _table_relation(self, name: str, binding: str,
                        local_views: dict) -> Relation:
        if name in local_views:
            node = local_views[name]
            return self._derived_relation(node, binding)
        if name in self.views:
            node = self.views[name]
            return self._derived_relation(node, binding)
        if not self.catalog.has_table(name):
            raise PlanError(f"unknown table {name!r}")
        schema: Schema = self.catalog.schemas[name]
        scan = P.Scan(name, binding,
                      [(f.name, f.dtype) for f in schema.fields])
        cols = {f.name: f.dtype for f in schema.fields}
        return Relation(binding, scan, cols,
                        size=self.catalog.sizes.get(name, 1000.0),
                        unique_on=tuple(self.catalog.primary_keys.get(name, ())))

    def _derived_relation(self, node: P.Node, binding: str) -> Relation:
        ds = P.DerivedScan(node, binding,
                           [(n, t) for n, t in node.output])
        cols = {n: t for n, t in node.output}
        return Relation(binding, ds, cols, size=10_000.0,
                        unique_on=_unique_key_of(node))

    # ------------------------------------------------------- main planning

    def plan_select(self, sel: ast.Select, outer: "Scope | None",
                    outer_views: dict) -> P.Node:
        local_views = dict(outer_views)
        for name, cte in sel.ctes.items():
            local_views[name] = self.plan_select(cte, outer, local_views)

        node = self._plan_core(sel, outer, local_views)

        for op, rhs in sel.set_ops:
            rnode = self._plan_core(rhs, outer, local_views)
            node = P.SetOp(op, node, rnode)
            if op in ("union", "intersect", "except"):
                node = P.Distinct(node)

        if sel.set_ops and (sel.order_by or sel.limit is not None):
            # over a set-op result, order keys can only name output columns
            node = self._plan_order_limit(node, sel)
        return node

    def _plan_order_limit(self, node: P.Node, sel: ast.Select) -> P.Node:
        # order keys resolve against the projected output by name
        if sel.order_by:
            scope = Scope()
            scope.add(Relation(node.binding, node,
                               {n: t for n, t in node.output}))
            keys = []
            for item in sel.order_by:
                e, depth = self._lower(item.expr, scope, allow_agg=False)
                keys.append((e, item.ascending, item.nulls_first))
            node = P.Sort(node, keys)
        if sel.limit is not None:
            node = P.Limit(node, sel.limit)
        return node

    def _plan_core(self, sel: ast.Select, outer: "Scope | None",
                   local_views: dict) -> P.Node:
        self._views_stack.append(local_views)
        try:
            return self._plan_core_inner(sel, outer, local_views)
        finally:
            self._views_stack.pop()

    def _plan_core_inner(self, sel: ast.Select, outer: "Scope | None",
                         local_views: dict) -> P.Node:
        scope = Scope(outer)
        ordered_rels: list[Relation] = []

        def add_source(src) -> Relation:
            if isinstance(src, ast.TableRef):
                rel = self._table_relation(src.name, src.binding, local_views)
            else:
                inner = self.plan_select(src.query, outer, local_views)
                rel = self._derived_relation(inner, src.alias)
            scope.add(rel)
            ordered_rels.append(rel)
            return rel

        for src in sel.from_tables:
            add_source(src)

        # conjunct classification state
        edges: list[tuple] = []        # (rel_a, key_ir_a, rel_b, key_ir_b)
        residuals: list[ir.IR] = []
        semis: list[P.SemiJoin] = []
        left_joins: list[tuple] = []   # (Relation, equi_pairs, residual)
        late: list[ir.IR] = []         # conjuncts touching left-join rels

        # explicit joins: INNER folds into the comma graph; LEFT is structural
        for jc in sel.joins:
            rel = add_source(jc.table)
            if jc.kind == "inner" or jc.kind == "cross":
                if jc.on is not None:
                    self._classify(_flatten_and(jc.on), scope, edges,
                                   residuals, semis, ordered_rels,
                                   local_views)
            elif jc.kind in ("left", "full"):
                pairs, resid = self._split_on(jc.on, scope, rel)
                if jc.kind == "full" and resid is not None:
                    raise PlanError(
                        "FULL OUTER JOIN supports only equi-conditions")
                left_joins.append((jc.kind, rel, pairs, resid))
                ordered_rels.remove(rel)  # not part of the inner-join graph
            else:
                raise PlanError(f"unsupported join kind {jc.kind}")

        left_bindings = {rel.binding for _k, rel, _p, _r in left_joins}
        has_full = any(k == "full" for k, _r, _p, _res in left_joins)
        if has_full:
            # a FULL join preserves BOTH sides: no WHERE conjunct may be
            # pushed below it (filtering the preserved side pre-join
            # changes which rows null-extend) — everything goes late
            left_bindings = left_bindings | {
                r.binding for r in ordered_rels}
        if sel.where is not None:
            conjuncts = _hoist_common_disjuncts(_flatten_and(sel.where))
            self._classify(conjuncts, scope, edges, residuals, semis,
                           ordered_rels, local_views,
                           external=left_bindings, late=late)

        # rels whose only connections go through a left-join output (q93's
        # `, reason where sr_reason_sk = r_reason_sk`) must join AFTER the
        # left join, or the graph would cross-join them
        deferred: list = []
        edge_bindings = set()
        for ra, _ia, rb, _ib in edges:
            edge_bindings.add(ra.binding if ra is not None else None)
            edge_bindings.add(rb.binding if rb is not None else None)
        for rel in list(ordered_rels):
            if has_full or rel.binding in edge_bindings:
                # under a FULL join every conjunct is late by design;
                # inner rels stay in the graph and late conjuncts become
                # post-join filters
                continue
            if any(rel.binding in self._bindings_of(e) for e in late):
                ordered_rels.remove(rel)
                deferred.append(rel)

        node = self._join_graph(ordered_rels, edges)

        for kind, rel, pairs, resid in left_joins:
            rnames = {p[1].name for p in pairs
                      if isinstance(p[1], ir.ColRef)}
            right_unique = (bool(rel.unique_on)
                            and set(rel.unique_on) <= rnames)
            node = P.Join(kind, node, rel.node,
                          [p[0] for p in pairs], [p[1] for p in pairs],
                          resid, right_unique=right_unique,
                          output=node.output + rel.node.output,
                          binding=node.binding)

        for rel in deferred:
            pairs2, rest = [], []
            for e in late:
                if isinstance(e, ir.Cmp) and e.op == "=":
                    lb = self._bindings_of(e.left)
                    rb = self._bindings_of(e.right)
                    if rb == {rel.binding} and rel.binding not in lb:
                        pairs2.append((e.left, e.right))
                        continue
                    if lb == {rel.binding} and rel.binding not in rb:
                        pairs2.append((e.right, e.left))
                        continue
                rest.append(e)
            late = rest
            rnames = {p[1].name for p in pairs2
                      if isinstance(p[1], ir.ColRef)}
            right_unique = (bool(rel.unique_on)
                            and set(rel.unique_on) <= rnames)
            node = P.Join("inner", node, rel.node,
                          [p[0] for p in pairs2], [p[1] for p in pairs2],
                          None, right_unique=right_unique,
                          output=node.output + rel.node.output,
                          binding=node.binding)
        residuals.extend(late)

        for s in semis:
            s.left = node
            node = s

        if residuals:
            node = P.Filter(node, self._conj(residuals))

        return self._plan_projection(sel, scope, node)

    # --------------------------------------------------- conjunct handling

    def _conj(self, preds: list[ir.IR]) -> ir.IR:
        return preds[0] if len(preds) == 1 else ir.BoolOp("and", preds)

    def _split_on(self, on: ast.Expr | None, scope: Scope, right: Relation):
        """Split a LEFT JOIN ON clause into equi pairs (left_ir, right_ir)
        and a residual over the combined row (q13's o_comment NOT LIKE
        lives in the ON clause, not WHERE)."""
        pairs, resid = [], []
        if on is None:
            return pairs, None
        for c in _flatten_and(on):
            e, _ = self._lower(c, scope, allow_agg=False)
            if (isinstance(e, ir.Cmp) and e.op == "="):
                lb = self._bindings_of(e.left)
                rb = self._bindings_of(e.right)
                if lb == {right.binding} and right.binding not in rb:
                    pairs.append((e.right, e.left))
                    continue
                if rb == {right.binding} and right.binding not in lb:
                    pairs.append((e.left, e.right))
                    continue
            resid.append(e)
        return pairs, (self._conj(resid) if resid else None)

    def _bindings_of(self, e: ir.IR) -> set:
        return {x.binding for x in ir.walk(e) if isinstance(x, ir.ColRef)}

    def _classify(self, conjuncts, scope, edges, residuals, semis,
                  rels, local_views, external: set | None = None,
                  late: list | None = None):
        by_binding = {r.binding: r for r in rels}
        for c in conjuncts:
            handled = self._try_subquery_conjunct(
                c, scope, edges, residuals, semis, rels, local_views,
                by_binding)
            if handled:
                continue
            e, depth = self._lower(c, scope, allow_agg=False)
            if external and (self._bindings_of(e) & external):
                # touches a left-join output: can only apply after the
                # left join is attached
                (late if late is not None else residuals).append(e)
                continue
            bs = self._bindings_of(e) & set(by_binding)
            if (isinstance(e, ir.Cmp) and e.op == "=" and len(bs) == 2):
                lb = self._bindings_of(e.left)
                rb = self._bindings_of(e.right)
                if len(lb) == 1 and len(rb) == 1 and lb != rb:
                    (a,), (b,) = lb, rb
                    if a in by_binding and b in by_binding:
                        edges.append((by_binding[a], e.left,
                                      by_binding[b], e.right))
                        continue
            if len(bs) == 1:
                rel = by_binding[next(iter(bs))]
                if isinstance(rel.node, P.Scan):
                    rel.node.filters.append(e)
                else:
                    rel.node = P.Filter(rel.node, e)
                rel.size *= 0.5
            else:
                residuals.append(e)

    # ------------------------------------------------------- subqueries

    def _try_subquery_conjunct(self, c, scope, edges, residuals, semis,
                               rels, local_views, by_binding) -> bool:
        neg = False
        inner_c = c
        while isinstance(inner_c, ast.UnaryOp) and inner_c.op == "not":
            neg = not neg
            inner_c = inner_c.operand

        if isinstance(inner_c, ast.Exists):
            self._plan_exists(inner_c.query, inner_c.negated ^ neg, scope,
                              semis, local_views)
            return True
        if isinstance(inner_c, ast.InSubquery):
            self._plan_in(inner_c, inner_c.negated ^ neg, scope, semis,
                          local_views)
            return True
        if isinstance(inner_c, ast.BinOp) and inner_c.op in (
                "=", "<>", "<", "<=", ">", ">="):
            for lhs, rhs, op in ((inner_c.left, inner_c.right, inner_c.op),
                                 (inner_c.right, inner_c.left,
                                  _flip(inner_c.op))):
                if isinstance(rhs, ast.ScalarSubquery):
                    if neg:
                        raise PlanError("NOT over scalar comparison "
                                        "unsupported")
                    self._plan_scalar_cmp(lhs, op, rhs.query, scope, edges,
                                          residuals, rels, by_binding,
                                          local_views)
                    return True
        return False

    def _subquery_context(self, sub: ast.Select, scope: Scope,
                          local_views: dict):
        """Plan a subquery's FROM/WHERE with `scope` as outer; returns
        (node, corr_pairs [(outer_ir, inner_ir)], corr_residuals,
        inner_scope)."""
        sub_planner_scope = Scope(scope)
        rels: list[Relation] = []
        for src in sub.from_tables:
            if isinstance(src, ast.TableRef):
                rel = self._table_relation(src.name, src.binding, local_views)
            else:
                inner = self.plan_select(src.query, scope, local_views)
                rel = self._derived_relation(inner, src.alias)
            sub_planner_scope.add(rel)
            rels.append(rel)
        if sub.joins:
            raise PlanError("explicit JOIN inside subquery not supported yet")
        if sub.set_ops:
            # would silently plan only the first branch — template must
            # wrap the union in a derived table instead
            raise PlanError("set operation directly inside IN/EXISTS "
                            "subquery: wrap it in a derived table")

        edges: list[tuple] = []
        residuals: list[ir.IR] = []
        semis: list[P.SemiJoin] = []
        corr_pairs: list[tuple] = []
        corr_resid: list[ir.IR] = []
        by_binding = {r.binding: r for r in rels}
        conjuncts = (_hoist_common_disjuncts(_flatten_and(sub.where))
                     if sub.where is not None else [])
        for c in conjuncts:
            handled = self._try_subquery_conjunct(
                c, sub_planner_scope, edges, residuals, semis, rels,
                local_views, by_binding)
            if handled:
                continue
            e, depth = self._lower(c, sub_planner_scope, allow_agg=False)
            local_bs = self._bindings_of(e) & set(by_binding)
            outer_bs = self._bindings_of(e) - set(by_binding)
            if outer_bs:
                # correlated conjunct: inner_expr = outer_expr becomes a
                # correlation key pair; anything else is a join residual
                if isinstance(e, ir.Cmp) and e.op == "=":
                    lb, rb = (self._bindings_of(e.left),
                              self._bindings_of(e.right))
                    l_local = bool(lb) and lb <= set(by_binding)
                    r_local = bool(rb) and rb <= set(by_binding)
                    l_outer = bool(lb) and not (lb & set(by_binding))
                    r_outer = bool(rb) and not (rb & set(by_binding))
                    if l_local and r_outer:
                        corr_pairs.append((e.right, e.left))
                        continue
                    if r_local and l_outer:
                        corr_pairs.append((e.left, e.right))
                        continue
                corr_resid.append(e)
                continue
            if (isinstance(e, ir.Cmp) and e.op == "=" and len(local_bs) == 2):
                lb = self._bindings_of(e.left)
                rb = self._bindings_of(e.right)
                if len(lb) == 1 and len(rb) == 1 and lb != rb:
                    edges.append((by_binding[next(iter(lb))], e.left,
                                  by_binding[next(iter(rb))], e.right))
                    continue
            if len(local_bs) == 1:
                rel = by_binding[next(iter(local_bs))]
                if isinstance(rel.node, P.Scan):
                    rel.node.filters.append(e)
                else:
                    rel.node = P.Filter(rel.node, e)
                rel.size *= 0.5
            else:
                residuals.append(e)

        node = self._join_graph(rels, edges)
        for s in semis:
            s.left = node
            node = s
        if residuals:
            node = P.Filter(node, self._conj(residuals))
        return node, corr_pairs, corr_resid, sub_planner_scope

    def _plan_exists(self, sub, anti, scope, semis, local_views):
        node, pairs, resid, _ = self._subquery_context(sub, scope,
                                                       local_views)
        if not pairs and not resid:
            raise PlanError("uncorrelated EXISTS not supported")
        semis.append(P.SemiJoin(
            None, node,
            [p[0] for p in pairs], [p[1] for p in pairs],
            self._conj(resid) if resid else None, anti))

    def _plan_in(self, node_ast: ast.InSubquery, anti, scope, semis,
                 local_views):
        sub = node_ast.query
        node, pairs, resid, sub_scope = self._subquery_context(
            sub, scope, local_views)
        if len(sub.items) != 1:
            raise PlanError("IN subquery must select one column")
        has_agg = (bool(sub.group_by) or sub.having is not None
                   or self._contains_agg(sub.items[0].expr))
        if has_agg:
            inner = self._plan_agg_subquery(sub, sub_scope, node)
            item_ir = ir.ColRef(inner.binding, inner.output[0][0],
                                inner.output[0][1])
            node = inner
        else:
            item_ir, _ = self._lower(sub.items[0].expr, sub_scope,
                                     allow_agg=False)
        outer_ir, _ = self._lower(node_ast.expr, scope, allow_agg=False)
        semis.append(P.SemiJoin(
            None, node,
            [outer_ir] + [p[0] for p in pairs],
            [item_ir] + [p[1] for p in pairs],
            self._conj(resid) if resid else None, anti))

    def _plan_agg_subquery(self, sub: ast.Select, sub_scope: Scope,
                           child: P.Node) -> P.Node:
        """Aggregate subquery used by IN (q18's having-stream)."""
        b = self._fresh("aggsub")
        group_keys = []
        for g in sub.group_by:
            e, _ = self._lower(g, sub_scope, allow_agg=False)
            name = e.name if isinstance(e, ir.ColRef) else self._fresh("k")
            group_keys.append((name, e))
        aggs: list[tuple[str, P.AggSpec]] = []

        def lower_with_aggs(e_ast):
            return self._lower(e_ast, sub_scope, allow_agg=True,
                               agg_sink=(aggs, sub_scope))

        item_ir, _ = lower_with_aggs(sub.items[0].expr)
        agg_node = P.Aggregate(child, group_keys, aggs, b)
        having_ir = None
        if sub.having is not None:
            having_ir, _ = lower_with_aggs(sub.having)
        # remap AggRef/group keys onto the aggregate's output columns
        out_node: P.Node = agg_node
        if having_ir is not None:
            out_node = P.Filter(out_node, self._remap_post_agg(
                having_ir, agg_node))
        proj = P.Project(out_node,
                         [("__in__", self._remap_post_agg(item_ir, agg_node))],
                         self._fresh("insub"))
        return proj

    def _plan_scalar_cmp(self, lhs_ast, op, sub, scope, edges, residuals,
                         rels, by_binding, local_views):
        node, pairs, resid, sub_scope = self._subquery_context(
            sub, scope, local_views)
        if resid:
            raise PlanError("non-equi correlation in scalar subquery")
        if len(sub.items) != 1:
            raise PlanError("scalar subquery must select one expression")
        aggs: list[tuple[str, P.AggSpec]] = []
        item_ir, _ = self._lower(sub.items[0].expr, sub_scope, allow_agg=True,
                                 agg_sink=(aggs, sub_scope))
        if not pairs:
            # uncorrelated: planned separately, bound at exec time
            if aggs:
                agg_node = P.Aggregate(node, [], aggs, self._fresh("scal"))
                value = self._remap_post_agg(item_ir, agg_node)
                root = P.Project(agg_node, [("__scalar__", value)],
                                 self._fresh("scalp"))
            else:
                root = P.Project(node, [("__scalar__", item_ir)],
                                 self._fresh("scalp"))
                if sub.distinct:
                    # (select distinct <expr> ...) used as a scalar
                    root = P.Distinct(root)
            sid = len(self.scalar_subplans)
            self.scalar_subplans.append(root)
            sref = ir.ScalarRef(sid, root.output[0][1])
            lhs_ir, _ = self._lower(lhs_ast, scope, allow_agg=False)
            pred = ir.Cmp(op, lhs_ir, sref)
            bs = self._bindings_of(pred) & set(by_binding)
            if len(bs) == 1:
                rel = by_binding[next(iter(bs))]
                if isinstance(rel.node, P.Scan):
                    rel.node.filters.append(pred)
                else:
                    rel.node = P.Filter(rel.node, pred)
            else:
                residuals.append(pred)
            return
        if not aggs:
            raise PlanError("correlated scalar subquery must aggregate")
        # correlated: aggregate grouped by the local half of each pair
        group_keys = []
        for i, (outer_ir, inner_ir) in enumerate(pairs):
            name = (inner_ir.name if isinstance(inner_ir, ir.ColRef)
                    else f"_ck{i}")
            group_keys.append((name, inner_ir))
        agg_node = P.Aggregate(node, group_keys, aggs, self._fresh("corr"))
        value = self._remap_post_agg(item_ir, agg_node)
        proj = P.Project(
            agg_node,
            [(n, ir.ColRef(agg_node.binding, n, t))
             for (n, _), t in zip(group_keys,
                                  [e.dtype for _, e in group_keys])]
            + [("__scalar__", value)],
            self._fresh("corrp"))
        rel = self._derived_relation(proj, proj.binding)
        rel.unique_on = tuple(n for n, _ in group_keys)
        rels.append(rel)
        by_binding[rel.binding] = rel
        for (outer_ir, _), (name, inner_ir) in zip(pairs, group_keys):
            edges.append((None, outer_ir, rel,
                          ir.ColRef(rel.binding, name, inner_ir.dtype)))
        lhs_ir, _ = self._lower(lhs_ast, scope, allow_agg=False)
        pred = ir.Cmp(op, lhs_ir,
                      ir.ColRef(rel.binding, "__scalar__",
                                proj.output[-1][1]))
        if op == "=":
            # equality against the scalar is itself a join edge
            edges.append((None, lhs_ir, rel,
                          ir.ColRef(rel.binding, "__scalar__",
                                    proj.output[-1][1])))
        else:
            residuals.append(pred)

    # ----------------------------------------------------------- join order

    def _join_graph(self, rels: list[Relation], edges: list[tuple]) -> P.Node:
        if not rels:
            raise PlanError("SELECT without FROM is not supported")
        # normalize edges: (binding_a, ir_a, binding_b, ir_b)
        norm = []
        for a, ia, b, ib in edges:
            ba = a.binding if a is not None else next(iter(
                self._bindings_of(ia)))
            bb = b.binding if b is not None else next(iter(
                self._bindings_of(ib)))
            norm.append((ba, ia, bb, ib))
        remaining = {r.binding: r for r in rels}
        # start from the PHYSICALLY largest relation: capacities are
        # static, so a filtered fact still occupies its full buffer —
        # it must be the probe side (discounted size would hand the
        # probe role to an unfiltered mid-size table and force an
        # expanding build over the fact, q12's 2x-capacity M:N trap)
        start = max(rels, key=lambda r: r.phys_size)
        current = start.node
        current_rel = start  # bare relation until the first join lands
        joined = {start.binding}
        del remaining[start.binding]
        pending = list(norm)
        while remaining:
            # candidate relations connected to the joined set
            cand: dict[str, list[tuple]] = {}
            for e in pending:
                ba, ia, bb, ib = e
                if ba in joined and bb in remaining:
                    cand.setdefault(bb, []).append((ia, ib))
                elif bb in joined and ba in remaining:
                    cand.setdefault(ba, []).append((ib, ia))
            if not cand:
                # disconnected: cross join the smallest remaining
                nxt = min(remaining.values(), key=lambda r: r.size)
                keys = ([], [])
                right_unique = False
            else:
                # prefer candidates UNIQUE on their join keys, then by
                # size: a unique build side makes every join a
                # key-preserving gather join on the device engine (no row
                # expansion, static output shape); joining a non-unique
                # side early (q5's customer-via-nationkey edge) would
                # force an expanding join the TPU plan can't bound
                def _uniq(b: str) -> bool:
                    r = remaining[b]
                    names = {k.name for _lk, k in cand[b]
                             if isinstance(k, ir.ColRef)}
                    return bool(r.unique_on) and set(r.unique_on) <= names
                best = min(cand, key=lambda b: (not _uniq(b),
                                                remaining[b].size))
                nxt = remaining[best]
                pairs = cand[best]
                keys = ([p[0] for p in pairs], [p[1] for p in pairs])
                right_unique = _uniq(best)
            build = nxt.node
            # the start-largest heuristic assumes the largest rel is a
            # fact (probe); in dimension-centric blocks (q10:
            # customer_demographics at 1.92M is the biggest rel but IS
            # the unique side of its first edge) that would run the
            # join as an expanding M:N at full capacity. While
            # `current` is still the bare start relation, flip the
            # sides so the unique start becomes the gather build.
            if not right_unique and current_rel is not None:
                snames = {k.name for k in keys[0]
                          if isinstance(k, ir.ColRef)
                          and k.binding == current_rel.binding}
                if (bool(current_rel.unique_on)
                        and set(current_rel.unique_on) <= snames):
                    current = nxt.node
                    build = current_rel.node
                    keys = (keys[1], keys[0])
                    right_unique = True
            current = P.Join("inner", current, build, keys[0], keys[1],
                             None, right_unique,
                             output=current.output + build.output,
                             binding=getattr(current, "binding", ""))
            current_rel = None
            joined.add(nxt.binding)
            del remaining[nxt.binding]
            pending = [e for e in pending
                       if not (e[0] in joined and e[2] in joined)]
        # leftover edges between already-joined rels -> filters
        for ba, ia, bb, ib in pending:
            current = P.Filter(current, ir.Cmp("=", ia, ib))
        return current

    # ------------------------------------------------------- projection/agg

    def _contains_agg(self, e: ast.Expr) -> bool:
        if isinstance(e, ast.FuncCall) and e.name in AGG_FUNCS:
            return True
        if isinstance(e, ast.WindowFunc):
            # a window's inputs may aggregate the enclosing GROUP BY
            # (rank() over (order by sum(x))); the window itself is not
            # an aggregate
            return any(self._contains_agg(a) for a in e.args) or any(
                self._contains_agg(p) for p in e.partition_by) or any(
                self._contains_agg(oi.expr) for oi in e.order_by)
        for v in vars(e).values():
            if isinstance(v, ast.Expr) and self._contains_agg(v):
                return True
            if isinstance(v, list):
                for x in v:
                    if isinstance(x, ast.Expr) and self._contains_agg(x):
                        return True
                    if isinstance(x, tuple):
                        if any(isinstance(y, ast.Expr)
                               and self._contains_agg(y) for y in x):
                            return True
        return False


    def _remap_post_agg(self, e: ir.IR, agg: P.Aggregate) -> ir.IR:
        """Rewrite AggRef -> ColRef(agg.binding, aggname) and group-key
        expressions -> ColRef(agg.binding, keyname)."""
        key_by_repr = {repr(k): (n, k.dtype) for n, k in agg.group_keys}

        def rec(x: ir.IR) -> ir.IR:
            if isinstance(x, ir.AggRef):
                name, spec = agg.aggs[x.index]
                return ir.ColRef(agg.binding, name, spec.dtype)
            if isinstance(x, ir.GroupingRef):
                # plain GROUP BY: every key participates -> constant 0
                return ir.Lit(0, INT32)
            r = repr(x)
            if r in key_by_repr:
                n, t = key_by_repr[r]
                return ir.ColRef(agg.binding, n, t)
            clone = x.__class__(**vars(x))
            for fname, v in vars(clone).items():
                if isinstance(v, ir.IR):
                    setattr(clone, fname, rec(v))
                elif isinstance(v, list):
                    setattr(clone, fname, [
                        tuple(rec(y) if isinstance(y, ir.IR) else y
                              for y in it) if isinstance(it, tuple)
                        else (rec(it) if isinstance(it, ir.IR) else it)
                        for it in v])
            return clone

        return rec(e)

    def _plan_projection(self, sel: ast.Select, scope: Scope,
                         node: P.Node) -> P.Node:
        has_agg = (bool(sel.group_by) or sel.having is not None
                   or any(self._contains_agg(it.expr) for it in sel.items))
        # expand stars
        items: list[ast.SelectItem] = []
        for it in sel.items:
            if isinstance(it.expr, ast.Star):
                for rel in scope.relations.values():
                    if it.expr.table and rel.binding != it.expr.table:
                        continue
                    for cname in rel.columns:
                        items.append(ast.SelectItem(
                            ast.Column(cname, rel.binding), cname))
            else:
                items.append(it)

        if not has_agg:
            wins: list[P.WindowSpec] = []
            exprs = []
            for i, it in enumerate(items):
                e, _ = self._lower(it.expr, scope, allow_agg=False,
                                   win_sink=wins)
                name = it.alias or (e.name if isinstance(e, ir.ColRef)
                                    else f"_c{i}")
                exprs.append((name, e))
            exprs = _dedupe_out_names(exprs)
            post: P.Node = node
            if wins:
                win_node, wremap = self._attach_window(
                    post, wins, lambda x: x)
                post = win_node
                exprs = [(n, wremap(e)) for n, e in exprs]
            proj = P.Project(post, exprs, self._fresh("proj"))
            if sel.distinct:
                out: P.Node = P.Distinct(proj)
                if not sel.set_ops and (sel.order_by
                                        or sel.limit is not None):
                    out = self._plan_order_limit(out, sel)
                return out
            if not sel.set_ops:
                return self._finish_select(proj, sel, scope, None, proj)
            return proj

        # aggregate path
        group_keys = []
        gk_map: dict[str, int] = {}
        for idx, g in enumerate(sel.group_by):
            e, _ = self._lower(g, scope, allow_agg=False)
            name = e.name if isinstance(e, ir.ColRef) else self._fresh("k")
            group_keys.append((name, e))
            gk_map[repr(e)] = idx
        aggs: list[tuple[str, P.AggSpec]] = []
        wins2: list[P.WindowSpec] = []
        lower_kw = dict(agg_sink=(aggs, scope), win_sink=wins2,
                        grouping_keys=gk_map)
        lowered_items = []
        for i, it in enumerate(items):
            e, _ = self._lower(it.expr, scope, allow_agg=True, **lower_kw)
            name = it.alias or (e.name if isinstance(e, ir.ColRef)
                                else f"_c{i}")
            lowered_items.append((name, e))
        lowered_items = _dedupe_out_names(lowered_items)
        having_ir = None
        if sel.having is not None:
            having_ir, _ = self._lower(sel.having, scope, allow_agg=True,
                                       **lower_kw)
        agg_node = None
        if sel.grouping_sets is not None:
            post, remap = self._plan_grouping_sets(
                node, group_keys, aggs, sel.grouping_sets)
        else:
            agg_node = P.Aggregate(node, group_keys, aggs,
                                   self._fresh("agg"))
            post = agg_node
            remap = lambda x: self._remap_post_agg(x, agg_node)  # noqa: E731
        if having_ir is not None:
            post = P.Filter(post, remap(having_ir))
        mapped_items = [(n, remap(e)) for n, e in lowered_items]
        if wins2:
            win_node, wremap = self._attach_window(post, wins2, remap)
            post = win_node
            mapped_items = [(n, wremap(e)) for n, e in mapped_items]
        proj = P.Project(post, mapped_items, self._fresh("proj"))
        if sel.distinct:
            out2: P.Node = P.Distinct(proj)
            if not sel.set_ops and (sel.order_by or sel.limit is not None):
                out2 = self._plan_order_limit(out2, sel)
            return out2
        if not sel.set_ops:
            return self._finish_select(
                proj, sel, scope,
                agg_node if sel.grouping_sets is None else None, proj)
        return proj

    def _attach_window(self, post: P.Node, wins: list, remap):
        """Build a Window node over `post` (specs remapped onto post's
        output namespace); returns (node, WindowRef-resolving remap)."""
        b = self._fresh("win")
        specs = []
        for i, s in enumerate(wins):
            specs.append((f"_win{i}", P.WindowSpec(
                s.func,
                remap(s.arg) if s.arg is not None else None,
                [remap(p) for p in s.partition],
                [(remap(e), asc, nf) for e, asc, nf in s.order],
                s.frame, s.dtype)))
        win_node = P.Window(post, specs, b)

        def wremap(x: ir.IR) -> ir.IR:
            return _replace_refs(x, lambda y: (
                ir.ColRef(b, f"_win{y.index}", y.dtype)
                if isinstance(y, ir.WindowRef) else None))

        return win_node, wremap

    def _plan_grouping_sets(self, child: P.Node, group_keys, aggs, sets):
        """Expand GROUP BY ROLLUP / GROUPING SETS into one Aggregate per
        set over the SHARED child (executors cache the child by node id,
        so it computes once), each projected onto a common column layout
        (rolled-up keys as typed NULLs + __grp markers), unioned ALL.
        Returns (union node, remap fn for item/having expressions)."""
        branches = []
        for S in sets:
            sset = set(S)
            agg_b = P.Aggregate(child, [group_keys[i] for i in S], aggs,
                                self._fresh("agg"))
            exprs: list = []
            for i, (name, e) in enumerate(group_keys):
                if i in sset:
                    exprs.append((name, ir.ColRef(agg_b.binding, name,
                                                  e.dtype)))
                else:
                    exprs.append((name, ir.Lit(None, e.dtype)))
            for i in range(len(group_keys)):
                exprs.append((f"__grp{i}",
                              ir.Lit(0 if i in sset else 1, INT32)))
            for aname, aspec in aggs:
                exprs.append((aname, ir.ColRef(agg_b.binding, aname,
                                               aspec.dtype)))
            branches.append(P.Project(agg_b, exprs, self._fresh("gsb")))
        union: P.Node = branches[0]
        for bnode in branches[1:]:
            union = P.SetOp("union all", union, bnode)
        out_bind = branches[0].binding
        key_by_repr = {repr(e): (n, e.dtype) for n, e in group_keys}

        def remap(x: ir.IR) -> ir.IR:
            def sub(y: ir.IR):
                if isinstance(y, ir.AggRef):
                    name, spec = aggs[y.index]
                    return ir.ColRef(out_bind, name, spec.dtype)
                if isinstance(y, ir.GroupingRef):
                    return ir.ColRef(out_bind, f"__grp{y.key_index}",
                                     INT32)
                r = repr(y)
                if r in key_by_repr:
                    n, t = key_by_repr[r]
                    return ir.ColRef(out_bind, n, t)
                return None
            return _replace_refs(x, sub)

        return union, remap

    def _finish_select(self, out: P.Node, sel: ast.Select, base_scope,
                       agg_node, proj: P.Project) -> P.Node:
        """ORDER BY / LIMIT for a plain (non-distinct, non-setop) select.

        SQL lets ORDER BY reference pre-projection columns and aggregates
        not in the select list (TPC-DS q19/q84/q96 order by base columns
        or bare aggregates). Resolution order: projected output names
        first, then the FROM scope (with agg remapping under GROUP BY);
        scope-resolved keys ride hidden projection columns that a final
        trim Project removes."""
        if not sel.order_by and sel.limit is None:
            return out
        if not sel.order_by:
            return P.Limit(out, sel.limit)
        visible = list(proj.output)
        out_scope = Scope()
        out_scope.add(Relation(proj.binding, proj,
                               {n: t for n, t in proj.output}))
        keys = []
        hidden = 0
        for item in sel.order_by:
            try:
                e, _ = self._lower(item.expr, out_scope, allow_agg=False)
            except PlanError:
                if agg_node is not None:
                    raw, _ = self._lower(item.expr, base_scope,
                                         allow_agg=True,
                                         agg_sink=(agg_node.aggs,
                                                   base_scope))
                    lowered = self._remap_post_agg(raw, agg_node)
                else:
                    lowered, _ = self._lower(item.expr, base_scope,
                                             allow_agg=False)
                name = f"__ord{hidden}"
                hidden += 1
                proj.exprs.append((name, lowered))
                e = ir.ColRef(proj.binding, name, lowered.dtype)
            keys.append((e, item.ascending, item.nulls_first))
        node: P.Node = P.Sort(out, keys)
        if sel.limit is not None:
            node = P.Limit(node, sel.limit)
        if hidden:
            node = P.Project(
                node, [(n, ir.ColRef(proj.binding, n, t))
                       for n, t in visible], self._fresh("trim"))
        return node

    # ------------------------------------------------------------- lowering

    def _lower(self, e: ast.Expr, scope: Scope, allow_agg: bool,
               agg_sink=None, win_sink=None, grouping_keys=None):
        """AST expr -> (ir.IR, max_outer_depth)."""
        depth_seen = [0]

        def rec(x: ast.Expr) -> ir.IR:
            if isinstance(x, ast.WindowFunc):
                if win_sink is None:
                    raise PlanError("window function not allowed here")
                arg_ir = rec(x.args[0]) if x.args else None
                part = [rec(p) for p in x.partition_by]
                order = [(rec(oi.expr), oi.ascending, oi.nulls_first)
                         for oi in x.order_by]
                if x.name in WINDOW_RANK_FUNCS:
                    dt = INT64
                else:
                    dt = ir.agg_type(
                        x.name, arg_ir.dtype if arg_ir is not None
                        else None)
                spec = P.WindowSpec(x.name, arg_ir, part, order,
                                    x.frame, dt)
                sig = (x.name, repr(arg_ir), repr(part), repr(order),
                       x.frame)
                for i, s in enumerate(win_sink):
                    if (s.func, repr(s.arg), repr(s.partition),
                            repr(s.order), s.frame) == sig:
                        return ir.WindowRef(i, s.dtype)
                win_sink.append(spec)
                return ir.WindowRef(len(win_sink) - 1, dt)
            if isinstance(x, ast.Column):
                ref, depth = scope.resolve(x)
                depth_seen[0] = max(depth_seen[0], depth)
                return ref
            if isinstance(x, ast.Literal):
                return self._lower_literal(x)
            if isinstance(x, ast.Interval):
                raise PlanError("bare interval outside date arithmetic")
            if isinstance(x, ast.BinOp):
                if x.op in ("and", "or"):
                    return ir.BoolOp(x.op, [rec(x.left), rec(x.right)])
                if x.op in ("=", "<>", "<", "<=", ">", ">="):
                    lhs, rhs = _coerce_date_cmp(rec(x.left),
                                                rec(x.right))
                    return ir.Cmp(x.op, lhs, rhs)
                # date ± interval folding
                if isinstance(x.right, ast.Interval):
                    base = rec(x.left)
                    iv = x.right
                    sign = 1 if x.op == "+" else -1
                    if isinstance(base, ir.Lit) and isinstance(
                            base.dtype, DateType):
                        if iv.unit == "day":
                            return ir.Lit(base.value + sign * iv.amount, DATE)
                        months = iv.amount * (12 if iv.unit == "year" else 1)
                        return ir.Lit(_add_months(base.value, sign * months),
                                      DATE)
                    if iv.unit == "day":
                        return ir.Arith(x.op, base,
                                        ir.Lit(iv.amount, INT32), DATE)
                    raise PlanError(
                        "month/year interval on non-literal date")
                l, r = rec(x.left), rec(x.right)
                return ir.Arith(x.op, l, r, ir.arith_type(
                    x.op, l.dtype, r.dtype))
            if isinstance(x, ast.UnaryOp):
                if x.op == "not":
                    return ir.Not(rec(x.operand))
                inner = rec(x.operand)
                if isinstance(inner, ir.Lit):
                    return ir.Lit(-inner.value, inner.dtype)
                return ir.Neg(inner, inner.dtype)
            if isinstance(x, ast.FuncCall):
                if x.name in AGG_FUNCS:
                    if not allow_agg or agg_sink is None:
                        raise PlanError(
                            f"aggregate {x.name} not allowed here")
                    aggs, agg_scope = agg_sink
                    if x.star:
                        spec = P.AggSpec("count", None, False, INT64)
                        arg_repr = "*"
                    else:
                        arg_ir, _ = self._lower(x.args[0], agg_scope, False)
                        spec = P.AggSpec(x.name, arg_ir, x.distinct,
                                         ir.agg_type(x.name, arg_ir.dtype))
                        arg_repr = repr(arg_ir)
                    sig = (x.name, arg_repr, x.distinct)
                    for i, (n, s) in enumerate(aggs):
                        if (s.func, repr(s.arg) if s.arg is not None
                                else "*", s.distinct) == sig:
                            return ir.AggRef(i, s.dtype)
                    name = f"_agg{len(aggs)}"
                    aggs.append((name, spec))
                    return ir.AggRef(len(aggs) - 1, spec.dtype)
                if x.name == "grouping":
                    if grouping_keys is None:
                        raise PlanError("grouping() outside GROUP BY "
                                        "ROLLUP/GROUPING SETS")
                    arg_ir = rec(x.args[0])
                    idx = grouping_keys.get(repr(arg_ir))
                    if idx is None:
                        raise PlanError(
                            f"grouping() argument {arg_ir!r} is not a "
                            "group key")
                    return ir.GroupingRef(idx)
                if x.name == "coalesce":
                    args = [rec(a) for a in x.args]
                    dt = args[0].dtype
                    for a in args[1:]:
                        if not isinstance(a, ir.Lit) or a.value is not None:
                            dt = _unify(dt, a.dtype)
                    whens = [(ir.IsNullIR(a, negated=True), a)
                             for a in args[:-1]]
                    return ir.CaseIR(whens, args[-1], dt)
                if x.name in ("upper", "lower"):
                    a = rec(x.args[0])
                    if isinstance(a, ir.Lit) and isinstance(a.value, str):
                        v = (a.value.upper() if x.name == "upper"
                             else a.value.lower())
                        return ir.Lit(v, StringType())
                    return ir.StrMapIR(x.name, a, StringType())
                if x.name == "concat":
                    parts = [rec(a) for a in x.args]
                    lits = [p.value if isinstance(p, ir.Lit) else None
                            for p in parts]
                    cols = [i for i, v in enumerate(lits) if v is None]
                    if not cols:  # all literals: fold
                        return ir.Lit("".join(str(v) for v in lits),
                                      StringType())
                    if len(cols) > 1:
                        raise PlanError(
                            "concat/|| supports one non-literal operand")
                    i = cols[0]
                    pre = "".join(str(v) for v in lits[:i])
                    suf = "".join(str(v) for v in lits[i + 1:])
                    return ir.ConcatIR(pre, parts[i], suf, StringType())
                if x.name == "nullif":
                    a, b = rec(x.args[0]), rec(x.args[1])
                    return ir.CaseIR([(ir.Cmp("=", a, b),
                                       ir.Lit(None, a.dtype))], a, a.dtype)
                if x.name == "round":
                    a = rec(x.args[0])
                    nd = 0
                    if len(x.args) > 1:
                        d = rec(x.args[1])
                        if not isinstance(d, ir.Lit):
                            raise PlanError("round() digits must be "
                                            "literal")
                        nd = int(d.value)
                    return ir.CastIR(a, DecimalType(38, nd))
                if x.name == "abs":
                    a = rec(x.args[0])
                    zero = ir.Lit(0, INT32)
                    return ir.CaseIR(
                        [(ir.Cmp("<", a, zero), ir.Neg(a, a.dtype))], a,
                        a.dtype)
                raise PlanError(f"unknown function {x.name}")
            if isinstance(x, ast.CaseWhen):
                whens = [(rec(c), rec(v)) for c, v in x.whens]
                else_ = rec(x.else_) if x.else_ is not None else None
                dt = whens[0][1].dtype
                for _, v in whens[1:]:
                    dt = _unify(dt, v.dtype)
                if else_ is not None:
                    dt = _unify(dt, else_.dtype)
                return ir.CaseIR(whens, else_, dt)
            if isinstance(x, ast.Between):
                e_ir = rec(x.expr)
                e_lo, lo = _coerce_date_cmp(e_ir, rec(x.low))
                e_hi, hi = _coerce_date_cmp(e_ir, rec(x.high))
                both = ir.BoolOp("and", [ir.Cmp(">=", e_lo, lo),
                                         ir.Cmp("<=", e_hi, hi)])
                return ir.Not(both) if x.negated else both
            if isinstance(x, ast.InList):
                e_ir = rec(x.expr)
                vals = []
                for item in x.items:
                    lit = _fold_const(rec(item))
                    if not isinstance(lit, ir.Lit):
                        raise PlanError("IN list items must be literals")
                    vals.append(lit.value)
                return ir.InListIR(e_ir, vals, x.negated)
            if isinstance(x, ast.Like):
                return ir.LikeIR(rec(x.expr), x.pattern, x.negated)
            if isinstance(x, ast.IsNull):
                return ir.IsNullIR(rec(x.expr), x.negated)
            if isinstance(x, ast.Extract):
                return ir.ExtractIR(x.part, rec(x.operand))
            if isinstance(x, ast.Substring):
                start = rec(x.start)
                length = rec(x.length) if x.length is not None else None
                if not isinstance(start, ir.Lit) or (
                        length is not None and not isinstance(length, ir.Lit)):
                    raise PlanError("SUBSTRING bounds must be literals")
                inner = rec(x.operand)
                return ir.SubstrIR(inner, start.value,
                                   None if length is None else length.value,
                                   StringType())
            if isinstance(x, ast.Cast):
                inner = rec(x.operand)
                t = {"int": INT64, "integer": INT64, "bigint": INT64,
                     "double": FLOAT64, "float": FLOAT64,
                     "decimal": DecimalType(38, 2), "date": DATE,
                     "varchar": StringType(), "char": StringType(),
                     "string": StringType()}.get(x.type_name)
                if t is None:
                    raise PlanError(f"unsupported cast to {x.type_name}")
                if (t is DATE and isinstance(inner, ir.Lit)
                        and isinstance(inner.value, str)):
                    # fold cast('1998-01-01' as date) to a DATE literal
                    # (q21/q40 style date-window arithmetic)
                    return ir.Lit(_date_to_days(inner.value), DATE)
                return ir.CastIR(inner, t)
            if isinstance(x, ast.ScalarSubquery):
                # uncorrelated scalar in a general expression position
                # (q11's HAVING threshold): plan separately, bind ScalarRef
                root = self.plan_select(x.query, scope,
                                        self._views_stack[-1])
                sid = len(self.scalar_subplans)
                self.scalar_subplans.append(root)
                return ir.ScalarRef(sid, root.output[0][1])
            if isinstance(x, (ast.InSubquery, ast.Exists)):
                raise PlanError(
                    "IN/EXISTS subquery in unsupported position (must be "
                    "a WHERE conjunct)")
            raise PlanError(f"cannot lower {x!r}")

        return rec(e), depth_seen[0]

    def _lower_literal(self, x: ast.Literal) -> ir.Lit:
        if x.kind == "int":
            return ir.Lit(x.value, INT32 if abs(x.value) < 2**31 else INT64)
        if x.kind == "decimal":
            s = x.value.split(".")[1] if "." in x.value else ""
            scale = len(s)
            scaled = int(round(float(x.value) * 10**scale))
            return ir.Lit(scaled, DecimalType(38, scale))
        if x.kind == "string":
            return ir.Lit(x.value, StringType())
        if x.kind == "date":
            return ir.Lit(_date_to_days(x.value), DATE)
        if x.kind == "null":
            return ir.Lit(None, BOOL)
        raise PlanError(f"unknown literal kind {x.kind}")


def _coerce_date_cmp(l: ir.IR, r: ir.IR) -> tuple:
    """SQL's implicit string->date cast in comparisons: a string literal
    compared against a DATE expression becomes a DATE literal (the
    reference engine gets this from Spark; the DF_* maintenance SQL and
    ad-hoc 'd_date between ...' predicates rely on it)."""
    from nds_tpu.engine.types import DateType
    if (isinstance(l.dtype, DateType) and isinstance(r, ir.Lit)
            and isinstance(r.dtype, StringType)
            and isinstance(r.value, str)):
        return l, ir.Lit(_date_to_days(r.value), DATE)
    if (isinstance(r.dtype, DateType) and isinstance(l, ir.Lit)
            and isinstance(l.dtype, StringType)
            and isinstance(l.value, str)):
        return ir.Lit(_date_to_days(l.value), DATE), r
    return l, r


def _unique_key_of(node: P.Node) -> tuple:
    """Output column names a derived table is unique on, traced through
    Project/Filter/Sort/Limit wrappers down to an Aggregate's group keys
    (q65's per-store average subquery is Project(Aggregate) — losing the
    key there forces expanding joins the device engine can't bound)."""
    if isinstance(node, P.Aggregate):
        return tuple(n for n, _ in node.group_keys)
    if isinstance(node, P.Distinct):
        return tuple(n for n, _ in node.output)
    if isinstance(node, (P.Filter, P.Sort, P.Limit)):
        return _unique_key_of(node.child)
    if isinstance(node, P.Window):
        # Window extends columns without changing the row set (q51's
        # cumulative sums over grouped CTEs stay unique on group keys)
        return _unique_key_of(node.child)
    if isinstance(node, P.Project):
        inner = _unique_key_of(node.child)
        if not inner:
            return ()
        # a Window child is namespace-EXTENDING: the Project reads key
        # columns under the Window's child binding, window columns under
        # the Window's own binding — accept both
        bindings = {getattr(node.child, "binding", "")}
        if isinstance(node.child, P.Window):
            bindings.add(getattr(node.child.child, "binding", ""))
        mapping = {}
        for name, e in node.exprs:
            if isinstance(e, ir.ColRef) and e.binding in bindings:
                mapping.setdefault(e.name, name)
        out = []
        for k in inner:
            if k not in mapping:
                return ()
            out.append(mapping[k])
        return tuple(out)
    return ()


def _replace_refs(e: ir.IR, sub) -> ir.IR:
    """Structurally clone `e`, replacing any node where sub(node) returns
    non-None (applied pre-order; replaced subtrees are not descended)."""
    if e is None:
        return None
    r = sub(e)
    if r is not None:
        return r
    clone = e.__class__(**vars(e))
    for fname, v in vars(clone).items():
        if isinstance(v, ir.IR):
            setattr(clone, fname, _replace_refs(v, sub))
        elif isinstance(v, list):
            setattr(clone, fname, [
                tuple(_replace_refs(y, sub) if isinstance(y, ir.IR) else y
                      for y in it) if isinstance(it, tuple)
                else (_replace_refs(it, sub) if isinstance(it, ir.IR)
                      else it)
                for it in v])
    return clone


def _fold_const(e: ir.IR) -> ir.IR:
    """Fold integer arithmetic over literals (IN (1999, 1999 + 1, ...))."""
    if isinstance(e, ir.Arith):
        l = _fold_const(e.left)
        r = _fold_const(e.right)
        if (isinstance(l, ir.Lit) and isinstance(r, ir.Lit)
                and isinstance(l.value, int) and isinstance(r.value, int)):
            v = {"+": l.value + r.value, "-": l.value - r.value,
                 "*": l.value * r.value}.get(e.op)
            if v is not None:
                return ir.Lit(v, e.dtype)
    return e


def _flip(op: str) -> str:
    return {"=": "=", "<>": "<>", "<": ">", "<=": ">=",
            ">": "<", ">=": "<="}[op]


def _unify(a: DType, b: DType) -> DType:
    if repr(a) == repr(b):
        return a
    if isinstance(a, FloatType) or isinstance(b, FloatType):
        return FLOAT64
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        sa = a.scale if isinstance(a, DecimalType) else 0
        sb = b.scale if isinstance(b, DecimalType) else 0
        return DecimalType(38, max(sa, sb))
    if isinstance(a, IntType) and isinstance(b, IntType):
        return INT64 if max(a.bits, b.bits) > 32 else INT32
    if isinstance(a, StringType) and isinstance(b, StringType):
        return StringType()
    raise PlanError(f"cannot unify {a} and {b}")
