"""SQL AST for the TPC dialect subset.

The reference delegates SQL parsing/planning to Spark Catalyst; there is no
Spark here, so the frontend is ours. Coverage target is the closed world of
the benchmark queries (TPC-H 22 + TPC-DS 99 as they land): select lists
with aliases, comma-FROM + explicit JOIN ... ON, derived tables, where /
group by / having / order by / limit, aggregates (incl. DISTINCT), CASE,
EXISTS / IN / scalar subqueries (correlated and not), date/interval
arithmetic, LIKE, EXTRACT, SUBSTRING, CTEs and set operations.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union


# --- expressions -----------------------------------------------------------

class Expr:
    pass


@dataclass
class Column(Expr):
    name: str
    table: Optional[str] = None  # qualifier as written (table name or alias)

    def __repr__(self):
        return f"{self.table}.{self.name}" if self.table else self.name


@dataclass
class Literal(Expr):
    value: object          # int | float-as-Decimal-string | str | None
    kind: str = "auto"     # auto|int|decimal|string|date|interval|null

    def __repr__(self):
        return f"{self.value!r}"


@dataclass
class Interval(Expr):
    amount: int
    unit: str              # day|month|year


@dataclass
class BinOp(Expr):
    op: str                # + - * / and or = <> < <= > >=
    left: Expr
    right: Expr

    def __repr__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclass
class UnaryOp(Expr):
    op: str                # not | -
    operand: Expr


@dataclass
class FuncCall(Expr):
    name: str              # lower-cased
    args: list[Expr] = field(default_factory=list)
    distinct: bool = False
    star: bool = False     # count(*)

    def __repr__(self):
        inner = "*" if self.star else ", ".join(map(repr, self.args))
        d = "distinct " if self.distinct else ""
        return f"{self.name}({d}{inner})"


@dataclass
class CaseWhen(Expr):
    whens: list[tuple[Expr, Expr]]
    else_: Optional[Expr] = None


@dataclass
class Cast(Expr):
    operand: Expr
    type_name: str


@dataclass
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    expr: Expr
    items: list[Expr]
    negated: bool = False


@dataclass
class Like(Expr):
    expr: Expr
    pattern: str
    negated: bool = False


@dataclass
class IsNull(Expr):
    expr: Expr
    negated: bool = False


@dataclass
class Extract(Expr):
    part: str              # year|month|day
    operand: Expr


@dataclass
class Substring(Expr):
    operand: Expr
    start: Expr
    length: Optional[Expr] = None


@dataclass
class ScalarSubquery(Expr):
    query: "Select"


@dataclass
class InSubquery(Expr):
    expr: Expr
    query: "Select"
    negated: bool = False


@dataclass
class Exists(Expr):
    query: "Select"
    negated: bool = False


@dataclass
class WindowFunc(Expr):
    """func(args) OVER (PARTITION BY ... ORDER BY ... [frame]).

    frame: None = default (whole partition for plain aggregates; the
    ranking functions ignore it); 'cum' = ROWS BETWEEN UNBOUNDED
    PRECEDING AND CURRENT ROW (running aggregate, TPC-DS q51)."""
    name: str
    args: list[Expr] = field(default_factory=list)
    partition_by: list[Expr] = field(default_factory=list)
    order_by: list["OrderItem"] = field(default_factory=list)
    frame: Optional[str] = None


@dataclass
class Star(Expr):
    table: Optional[str] = None


# --- relations -------------------------------------------------------------

@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None

    @property
    def binding(self) -> str:
        return self.alias or self.name


@dataclass
class SubqueryRef:
    query: "Select"
    alias: str

    @property
    def binding(self) -> str:
        return self.alias


@dataclass
class JoinClause:
    kind: str              # inner|left|right|full|cross
    table: Union[TableRef, SubqueryRef]
    on: Optional[Expr] = None


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass
class OrderItem:
    expr: Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclass
class CreateView:
    """CREATE [TEMP] VIEW name [(col, ...)] AS select — q15 part 1
    (`nds-h/nds_h_power.py:78-82` runs the three statements separately)."""
    name: str
    columns: list[str]
    query: "Select"


@dataclass
class DropView:
    name: str
    if_exists: bool = False


@dataclass
class Insert:
    """INSERT INTO table (select ...) — the LF_* refresh functions'
    second statement (`nds/data_maintenance/LF_SS.sql` last line)."""
    table: str
    query: "Select"


@dataclass
class Delete:
    """DELETE FROM table WHERE pred — the DF_* refresh functions
    (`nds/data_maintenance/DF_SS.sql`)."""
    table: str
    where: Optional[Expr]


@dataclass
class Select:
    items: list[SelectItem] = field(default_factory=list)
    distinct: bool = False
    from_tables: list[Union[TableRef, SubqueryRef]] = field(default_factory=list)
    joins: list[JoinClause] = field(default_factory=list)
    where: Optional[Expr] = None
    group_by: list[Expr] = field(default_factory=list)
    # GROUP BY ROLLUP(...) / GROUPING SETS(...): list of grouping sets,
    # each a list of indexes into group_by. None = plain GROUP BY.
    grouping_sets: Optional[list[list[int]]] = None
    having: Optional[Expr] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    # set operations: (op, select) applied left-to-right; op in
    # union|union all|intersect|except
    set_ops: list[tuple[str, "Select"]] = field(default_factory=list)
    # WITH ctes visible to this select (name -> Select)
    ctes: dict = field(default_factory=dict)
