"""Logical plan nodes.

The closed-world benchmark workload means plans are fixed per query; the
executor walks this tree. Join nodes carry equi-keys explicitly (the
engine's join strategies key off them) plus an optional residual predicate;
semi/anti joins are first-class because EXISTS/IN decorrelation produces
them (q4/q16/q18/q20/q21/q22).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from nds_tpu.engine.types import DType
from nds_tpu.sql import ir


class Node:
    """Base logical plan node. ``output`` lists (name, dtype) columns; each
    node's output columns are addressable as ColRef(binding, name)."""
    output: list[tuple[str, DType]]
    binding: str


@dataclass
class Scan(Node):
    table: str
    binding: str
    output: list = field(default_factory=list)
    # conjunctive pushed-down predicates over this table's columns
    filters: list = field(default_factory=list)


@dataclass
class DerivedScan(Node):
    """A planned derived table / view / CTE with its own binding."""
    child: "Node" = None
    binding: str = ""
    output: list = field(default_factory=list)


@dataclass
class StagedScan(Node):
    """Scan of a host-staged intermediate (plan splitting,
    engine/staging.py): reads the temp table behind ``child`` (a plain
    Scan with mangled column names) and re-exposes each column under its
    ORIGINAL (binding, name) address so ancestor nodes compile
    unchanged. Created by the executor, never by the planner."""
    child: Scan = None
    # [(orig_binding, orig_name, mangled_name, dtype)]
    cols: list = field(default_factory=list)
    binding: str = ""
    output: list = field(default_factory=list)


@dataclass
class Filter(Node):
    child: Node = None
    predicate: ir.IR = None

    @property
    def output(self):
        return self.child.output

    @property
    def binding(self):
        return self.child.binding


@dataclass
class Project(Node):
    child: Node = None
    exprs: list = field(default_factory=list)   # list[(name, ir.IR)]
    binding: str = ""

    @property
    def output(self):
        return [(n, e.dtype) for n, e in self.exprs]


@dataclass
class Join(Node):
    kind: str = "inner"          # inner|left
    left: Node = None
    right: Node = None
    left_keys: list = field(default_factory=list)    # list[ir.IR]
    right_keys: list = field(default_factory=list)
    residual: Optional[ir.IR] = None  # evaluated over combined columns
    # True when right side is unique on right_keys (PK side): the engine
    # uses the gather join path; otherwise the expanding join path
    right_unique: bool = False
    output: list = field(default_factory=list)
    binding: str = ""
    # kernel choice (engine/kernels.py): stamped by the planner from
    # catalog size estimates; "" = legacy trace heuristics. Lives ON
    # the node so the AOT plan fingerprint distinguishes kernel choices
    kernel: str = ""


@dataclass
class SemiJoin(Node):
    """EXISTS/IN (anti=False) and NOT EXISTS/NOT IN (anti=True).
    Residual may reference both sides (q21's l2.l_suppkey <> l1.l_suppkey)."""
    left: Node = None
    right: Node = None
    left_keys: list = field(default_factory=list)
    right_keys: list = field(default_factory=list)
    residual: Optional[ir.IR] = None
    anti: bool = False
    # kernel choice (engine/kernels.py): "bitmask" membership tables vs
    # "sortmerge" gather machinery; "" = legacy
    kernel: str = ""

    @property
    def output(self):
        return self.left.output

    @property
    def binding(self):
        return self.left.binding


@dataclass
class AggSpec:
    func: str                    # sum|avg|min|max|count
    arg: Optional[ir.IR]         # None for count(*)
    distinct: bool = False
    dtype: DType = None


@dataclass
class Aggregate(Node):
    child: Node = None
    group_keys: list = field(default_factory=list)   # list[(name, ir.IR)]
    aggs: list = field(default_factory=list)         # list[(name, AggSpec)]
    binding: str = ""
    # kernel choice (engine/kernels.py): "segscan" scan-based grouped
    # min/max vs "scatter" segment_min/max; "" = legacy (scatter)
    kernel: str = ""

    @property
    def output(self):
        return ([(n, e.dtype) for n, e in self.group_keys]
                + [(n, a.dtype) for n, a in self.aggs])


@dataclass
class WindowSpec:
    """One window column. frame: None = SQL default (whole partition
    when there is no ORDER BY; RANGE UNBOUNDED PRECEDING..CURRENT ROW —
    running with ties sharing a value — when there is); 'cum' = ROWS
    UNBOUNDED PRECEDING..CURRENT ROW. Ranking funcs ignore frame."""
    func: str                 # rank|dense_rank|row_number|sum|avg|min|max|count
    arg: Optional[ir.IR]
    partition: list = field(default_factory=list)   # list[ir.IR]
    order: list = field(default_factory=list)  # (ir.IR, asc, nulls_first)
    frame: Optional[str] = None
    dtype: DType = None


@dataclass
class Window(Node):
    """Namespace-extending operator: keeps the child's row set and adds
    one column per spec under this node's own binding (a Project above
    reads both namespaces)."""
    child: Node = None
    specs: list = field(default_factory=list)       # list[(name, WindowSpec)]
    binding: str = ""

    @property
    def output(self):
        return [(n, s.dtype) for n, s in self.specs]


@dataclass
class Sort(Node):
    child: Node = None
    keys: list = field(default_factory=list)  # list[(ir.IR, ascending, nulls_first)]

    @property
    def output(self):
        return self.child.output

    @property
    def binding(self):
        return self.child.binding


@dataclass
class Limit(Node):
    child: Node = None
    count: int = 0

    @property
    def output(self):
        return self.child.output

    @property
    def binding(self):
        return self.child.binding


@dataclass
class Distinct(Node):
    child: Node = None

    @property
    def output(self):
        return self.child.output

    @property
    def binding(self):
        return self.child.binding


@dataclass
class SetOp(Node):
    kind: str = "union all"     # union|union all|intersect|except
    left: Node = None
    right: Node = None

    @property
    def output(self):
        return self.left.output

    @property
    def binding(self):
        return self.left.binding


@dataclass
class PlannedQuery:
    """Root of one statement: the plan plus its uncorrelated scalar
    subplans (evaluated first, results bound to ScalarRef ids)."""
    root: Node = None
    scalar_subplans: list = field(default_factory=list)  # list[PlannedQuery-ish Node]
    column_names: list = field(default_factory=list)


def children(node: Node):
    for attr in ("child", "left", "right"):
        c = getattr(node, attr, None)
        if isinstance(c, Node):
            yield c


def walk_plan(node: Node):
    yield node
    for c in children(node):
        yield from walk_plan(c)


def all_exprs(node: Node):
    """Yield every ir.IR expression attached to a single node."""
    if isinstance(node, Scan):
        yield from node.filters
    elif isinstance(node, Filter):
        yield node.predicate
    elif isinstance(node, Project):
        for _, e in node.exprs:
            yield e
    elif isinstance(node, (Join, SemiJoin)):
        yield from node.left_keys
        yield from node.right_keys
        if node.residual is not None:
            yield node.residual
    elif isinstance(node, Aggregate):
        for _, e in node.group_keys:
            yield e
        for _, a in node.aggs:
            if a.arg is not None:
                yield a.arg
    elif isinstance(node, Sort):
        for e, _, _ in node.keys:
            yield e
    elif isinstance(node, Window):
        for _, s in node.specs:
            if s.arg is not None:
                yield s.arg
            yield from s.partition
            for e, _, _ in s.order:
                yield e
