"""Parameterized plans: hoist query literals into runtime arguments.

The 121 NDS + NDS-H templates generate an unbounded query population
that differs only in substitution literals (dsqgen/qgen ``-rngseed``).
Before this module every literal baked into the traced XLA program as a
constant, so each literal variant was a distinct plan, a distinct AOT
fingerprint, and a distinct compile.  ``parameterize()`` rewrites a
freshly planned statement so the literals become indexed parameter
slots (the Execution Templates idea: cache the expensive control-plane
decision once, validate/bind cheaply per request):

- plain numeric/date/decimal literals -> ``ir.ParamRef`` (a runtime
  scalar input);
- string predicates bound against a column dictionary (LIKE,
  comparisons, IN-lists) -> ``ir.DictParamIR`` (the device program
  takes a boolean table over the dictionary as input; ``bind_params``
  computes it on the host per request);
- numeric IN-lists -> ``ir.InListParamIR`` (a fixed-width vector
  input).

The literal VALUES ride on ``planned.param_values`` — a plain
attribute, not a dataclass field — so the fingerprint's ``canonical()``
walk never sees them: two literal variants of one template hash to ONE
cache entry and share one compiled program, with zero per-request
compiles.

Only the device executor evaluates parameter nodes natively
(``_Trace``); every other executor (CPU oracle, chunked, sharded)
calls ``inline()`` at entry, which substitutes the literals back and
runs the exact pre-parameterization plan — correctness never depends
on a placement understanding parameters.  What is NOT hoisted (the
value would shape host-side trace constants or plan structure):
NULL literals, string literals outside dictionary-resolvable
predicates, SUBSTRING bounds, LIMIT counts, and anything inside join
keys / group keys / sort keys (key packing and kernel feasibility read
value bounds there).  A scan filter that becomes parameterized also
opts out of the host keep-mask reduction — deterministically for every
variant, so the program shape still matches across the template.
"""

from __future__ import annotations

import os

import numpy as np

from nds_tpu.engine.types import (
    DateType, DecimalType, FloatType, IntType, StringType,
)
from nds_tpu.sql import ir
from nds_tpu.sql import plan as P

ENV_FLAG = "NDS_TPU_PARAM_PLANS"


def enabled_by_env() -> bool:
    return os.environ.get(ENV_FLAG, "0") not in ("0", "", "false")


def has_params(planned) -> bool:
    return bool(getattr(planned, "param_values", None))


# ------------------------------------------------------------- cloning

def _clone_ir(e, memo: dict):
    if e is None or not isinstance(e, ir.IR):
        return e
    hit = memo.get(id(e))
    if hit is not None:
        return hit
    clone = e.__class__(**vars(e))
    # ndslint: waive[NDS101] -- memo is call-local; the source tree is pinned by the caller for the whole clone
    memo[id(e)] = clone
    for fname, v in vars(clone).items():
        setattr(clone, fname, _clone_val(v, memo))
    return clone


def _clone_val(v, memo: dict):
    if isinstance(v, ir.IR):
        return _clone_ir(v, memo)
    if isinstance(v, P.Node):
        return _clone_node(v, memo)
    if isinstance(v, P.AggSpec):
        return P.AggSpec(v.func, _clone_ir(v.arg, memo), v.distinct,
                         v.dtype)
    if isinstance(v, P.WindowSpec):
        return P.WindowSpec(
            v.func, _clone_ir(v.arg, memo),
            [_clone_ir(p, memo) for p in v.partition],
            [(_clone_ir(e, memo), a, nf) for e, a, nf in v.order],
            v.frame, v.dtype)
    if isinstance(v, tuple):
        return tuple(_clone_val(x, memo) for x in v)
    if isinstance(v, list):
        return [_clone_val(x, memo) for x in v]
    return v


def _clone_node(node, memo: dict):
    """Deep-copy a plan tree PRESERVING shared subtrees (CTE bodies and
    session views are referenced from multiple parents; executors dedup
    work by node identity, so the clone must keep one copy per source
    node — and must never mutate the session-owned originals)."""
    if node is None:
        return None
    hit = memo.get(id(node))
    if hit is not None:
        return hit
    # __new__ (not __init__): nodes with required positional fields
    # clone the same way; the memo entry must exist BEFORE children
    # clone so shared subtrees resolve to one copy
    clone = object.__new__(node.__class__)
    # ndslint: waive[NDS101] -- memo is call-local; the source tree is pinned by the caller for the whole clone
    memo[id(node)] = clone
    for fname, v in vars(node).items():
        setattr(clone, fname, _clone_val(v, memo))
    return clone


def clone_planned(planned: P.PlannedQuery) -> P.PlannedQuery:
    memo: dict = {}
    return P.PlannedQuery(
        _clone_node(planned.root, memo),
        [_clone_node(s, memo) for s in planned.scalar_subplans],
        list(planned.column_names))


# ------------------------------------------------------ parameterizing

_HOISTABLE_SCALAR = (IntType, FloatType, DecimalType, DateType)

# string-operand transform chain the host binder can replicate (each is
# a deterministic per-dictionary-entry rewrite; see derive_dictionary)
_DICT_CHAIN = (ir.SubstrIR, ir.StrMapIR, ir.ConcatIR)


def _scan_binding_map(planned: P.PlannedQuery) -> dict:
    """binding -> table for every base-table Scan; a binding reused for
    DIFFERENT tables (alias collision across scopes) maps to None and
    opts its predicates out of dictionary hoisting."""
    out: dict = {}
    for root in [planned.root, *planned.scalar_subplans]:
        for node in P.walk_plan(root):
            if isinstance(node, P.Scan):
                prev = out.get(node.binding)
                if prev is not None and prev != node.table:
                    out[node.binding] = None
                else:
                    out.setdefault(node.binding, node.table)
    return out


def _derived_col_map(planned: P.PlannedQuery) -> dict:
    """(binding, name) -> defining expression, for columns exposed by
    namespace-mapping nodes: a DerivedScan re-exposes its child's
    columns, a Project names expressions, an Aggregate names its group
    keys.  Lets ``_dict_source`` trace a predicate on a derived-table
    alias (q8's ``nation = '...'``) back to the base scan column whose
    dictionary the value rides — codes carry their source dictionary
    unchanged through joins and derived scans. Ambiguous (binding,
    name) pairs map to None (no hoist)."""
    out: dict = {}

    def put(key, expr):
        if key in out and repr(out[key]) != repr(expr):
            out[key] = None
        else:
            out.setdefault(key, expr)

    for root in [planned.root, *planned.scalar_subplans]:
        for node in P.walk_plan(root):
            if isinstance(node, P.DerivedScan):
                cb = node.child.binding
                for name, dt in node.child.output:
                    put((node.binding, name),
                        ir.ColRef(cb, name, dt))
            elif isinstance(node, P.Project):
                for name, e in node.exprs:
                    put((node.binding, name), e)
            elif isinstance(node, P.Aggregate):
                for name, e in node.group_keys:
                    put((node.binding, name), e)
    return out


def _chain_step(e) -> tuple:
    if isinstance(e, ir.StrMapIR):
        return ("map", e.op)
    if isinstance(e, ir.ConcatIR):
        return ("concat", e.prefix, e.suffix)
    return ("substr", e.start, e.length)


def _dict_source(e, scan_map: dict, catalog,
                 deriv_map: "dict | None" = None) -> "tuple | None":
    """(table, column, chain_spec) for the base-table dictionary the
    operand's value rides, or None when the chain is not
    host-replicable. ``chain_spec`` lists the string transforms —
    accumulated across derived-table alias hops, innermost-first — the
    binder must replay on the base dictionary, so the host table
    matches what the trace applies even when a Project along the way
    did the transforming."""
    steps: list = []  # outermost-first
    for _hop in range(16):  # alias-chain depth guard
        while isinstance(e, _DICT_CHAIN):
            steps.append(_chain_step(e))
            e = e.operand
        if not isinstance(e, ir.ColRef):
            return None
        if not isinstance(e.dtype, StringType):
            return None
        table = scan_map.get(e.binding)
        if table is not None:
            break
        nxt = (deriv_map or {}).get((e.binding, e.name))
        if nxt is None:
            return None
        e = nxt
    else:
        return None
    if catalog is not None:
        schema = catalog.schemas.get(table)
        if schema is None or not any(
                f.name == e.name and isinstance(f.dtype, StringType)
                for f in schema.fields):
            return None
    return table, e.name, tuple(reversed(steps))


class _Parameterizer:
    def __init__(self, planned: P.PlannedQuery, catalog=None):
        self.values: list = []
        self.scan_map = _scan_binding_map(planned)
        self.deriv_map = _derived_col_map(planned)
        self.catalog = catalog

    def _slot(self, value) -> int:
        self.values.append(value)
        return len(self.values) - 1

    def _source(self, operand):
        return _dict_source(operand, self.scan_map, self.catalog,
                            self.deriv_map)

    # ------------------------------------------------- expression pass

    def rewrite(self, e):
        """Hoist literals inside one expression tree (returns the
        rewritten expression; mutates cloned nodes only)."""
        if e is None or not isinstance(e, ir.IR):
            return e
        if isinstance(e, ir.Lit):
            return self._hoist_lit(e)
        if isinstance(e, ir.Cmp):
            return self._rewrite_cmp(e)
        if isinstance(e, ir.LikeIR):
            return self._rewrite_like(e)
        if isinstance(e, ir.InListIR):
            return self._rewrite_inlist(e)
        self._rewrite_fields(e)
        return e

    def _rewrite_fields(self, e) -> None:
        for fname, v in vars(e).items():
            if isinstance(v, ir.IR):
                setattr(e, fname, self.rewrite(v))
            elif isinstance(v, list):
                setattr(e, fname, [
                    tuple(self.rewrite(y) if isinstance(y, ir.IR) else y
                          for y in it) if isinstance(it, tuple)
                    else (self.rewrite(it) if isinstance(it, ir.IR)
                          else it)
                    for it in v])

    def _hoist_lit(self, e: ir.Lit):
        if e.value is None:
            return e
        if isinstance(e.dtype, _HOISTABLE_SCALAR):
            return ir.ParamRef(self._slot(e.value), e.dtype)
        return e  # strings/bools only hoist via dictionary predicates

    def _rewrite_cmp(self, e: ir.Cmp):
        lt, rt = e.left.dtype, e.right.dtype
        if isinstance(lt, StringType) or isinstance(rt, StringType):
            lit, operand, op = None, None, e.op
            if isinstance(e.right, ir.Lit) and isinstance(
                    e.right.value, str):
                lit, operand = e.right.value, e.left
            elif isinstance(e.left, ir.Lit) and isinstance(
                    e.left.value, str):
                lit, operand = e.left.value, e.right
                op = {"<": ">", "<=": ">=", ">": "<",
                      ">=": "<="}.get(op, op)
            if lit is not None:
                src = self._source(operand)
                if src is not None:
                    return ir.DictParamIR(
                        operand, src[0], src[1], "cmp", op,
                        self._slot(lit), chain=src[2])
            return e  # string compare the binder can't replicate
        self._rewrite_fields(e)
        return e

    def _rewrite_like(self, e: ir.LikeIR):
        src = self._source(e.operand)
        if src is None:
            return e
        return ir.DictParamIR(e.operand, src[0], src[1], "like", "",
                              self._slot(e.pattern), e.negated,
                              chain=src[2])

    def _rewrite_inlist(self, e: ir.InListIR):
        if not e.values or any(v is None for v in e.values):
            return e
        if isinstance(e.operand.dtype, StringType):
            src = self._source(e.operand)
            if src is None:
                return e
            return ir.DictParamIR(
                e.operand, src[0], src[1], "inlist", "",
                self._slot(tuple(str(v) for v in e.values)), e.negated,
                chain=src[2])
        if isinstance(e.operand.dtype, _HOISTABLE_SCALAR):
            return ir.InListParamIR(
                e.operand, self._slot(tuple(e.values)), len(e.values),
                e.negated)
        return e

    # -------------------------------------------------------- node pass

    def visit(self, root: P.Node) -> None:
        seen: set = set()
        for node in P.walk_plan(root):
            if id(node) in seen:
                continue
            seen.add(id(node))
            if isinstance(node, P.Scan):
                node.filters = [self.rewrite(f) for f in node.filters]
            elif isinstance(node, P.Filter):
                node.predicate = self.rewrite(node.predicate)
            elif isinstance(node, P.Project):
                node.exprs = [(n, self.rewrite(e))
                              for n, e in node.exprs]
            elif isinstance(node, (P.Join, P.SemiJoin)):
                # keys stay inlined: packing/kernel feasibility reads
                # their value bounds — only the residual hoists
                if node.residual is not None:
                    node.residual = self.rewrite(node.residual)
            elif isinstance(node, P.Aggregate):
                # group keys stay inlined (grouping packs by bounds);
                # aggregate ARguments hoist (sum(price * lit))
                node.aggs = [
                    (n, P.AggSpec(a.func, self.rewrite(a.arg),
                                  a.distinct, a.dtype))
                    for n, a in node.aggs]


def parameterize(planned: P.PlannedQuery,
                 catalog=None) -> P.PlannedQuery:
    """Clone + hoist. Returns the clone with ``param_values`` attached
    (an empty hoist returns the clone with no attribute, so downstream
    fast paths stay no-ops). The session-owned original — including any
    shared view bodies — is never mutated."""
    clone = clone_planned(planned)
    pz = _Parameterizer(clone, catalog)
    for root in [clone.root, *clone.scalar_subplans]:
        pz.visit(root)
    if pz.values:
        clone.param_values = pz.values
    return clone


# ---------------------------------------------------------- inlining

def plan_key(planned) -> "tuple | None":
    """The shared-program cache key for a parameterized plan:
    ``("param", <canonical plan digest>)``, memoized on the plan
    object. One helper, used by BOTH the device executor's compile
    cache (device_exec._plan_key) and the server's template batching
    (serve/server.py), so the two can never drift apart. None for
    unparameterized plans."""
    if not has_params(planned):
        return None
    memo = getattr(planned, "_param_key_memo", None)
    if memo is None:
        from nds_tpu.cache.fingerprint import plan_digest
        memo = ("param", plan_digest(planned))
        try:
            planned._param_key_memo = memo
        except Exception:  # noqa: BLE001 - slotted plan: recompute
            pass
    return memo


def inline(planned: P.PlannedQuery) -> P.PlannedQuery:
    """Substitute the literal values back: the exact plan the
    pre-parameterization planner produced, for executors that evaluate
    literals as constants (CPU oracle, chunked, sharded). No-op (same
    object) for unparameterized plans. The clone is memoized on the
    parameterized plan: repeated dispatches of one cached plan (a
    serving workload's sharded/streamed placements) keep ONE stable
    inlined object, so id-keyed executor caches keep hitting instead
    of recompiling per request."""
    values = getattr(planned, "param_values", None)
    if not values:
        return planned
    memo = getattr(planned, "_inline_memo", None)
    if memo is not None:
        return memo
    clone = clone_planned(planned)

    def sub(e):
        if isinstance(e, ir.ParamRef):
            return ir.Lit(values[e.index], e.dtype)
        if isinstance(e, ir.InListParamIR):
            return ir.InListIR(rec(e.operand), list(values[e.index]),
                               e.negated)
        if isinstance(e, ir.DictParamIR):
            v = values[e.index]
            if e.kind == "like":
                return ir.LikeIR(rec(e.operand), v, e.negated)
            if e.kind == "inlist":
                return ir.InListIR(rec(e.operand), list(v), e.negated)
            return ir.Cmp(e.op, rec(e.operand),
                          ir.Lit(v, StringType()))
        return None

    def rec(e):
        if e is None or not isinstance(e, ir.IR):
            return e
        r = sub(e)
        if r is not None:
            return r
        for fname, v in vars(e).items():
            if isinstance(v, ir.IR):
                setattr(e, fname, rec(v))
            elif isinstance(v, list):
                setattr(e, fname, [
                    tuple(rec(y) if isinstance(y, ir.IR) else y
                          for y in it) if isinstance(it, tuple)
                    else (rec(it) if isinstance(it, ir.IR) else it)
                    for it in v])
        return e

    seen: set = set()
    for root in [clone.root, *clone.scalar_subplans]:
        for node in P.walk_plan(root):
            if id(node) in seen:
                continue
            seen.add(id(node))
            if isinstance(node, P.Scan):
                node.filters = [rec(f) for f in node.filters]
            elif isinstance(node, P.Filter):
                node.predicate = rec(node.predicate)
            elif isinstance(node, P.Project):
                node.exprs = [(n, rec(e)) for n, e in node.exprs]
            elif isinstance(node, (P.Join, P.SemiJoin)):
                if node.residual is not None:
                    node.residual = rec(node.residual)
            elif isinstance(node, P.Aggregate):
                node.aggs = [(n, P.AggSpec(a.func, rec(a.arg),
                                           a.distinct, a.dtype))
                             for n, a in node.aggs]
    try:
        planned._inline_memo = clone
    except Exception:  # noqa: BLE001 - slotted plan: re-clone next time
        pass
    return clone


# ----------------------------------------------------------- binding

def scalar_np_dtype(dt) -> "np.dtype":
    """The FIXED numpy dtype a hoisted scalar binds at — independent of
    the value, so every literal variant lowers to the same program
    signature."""
    if isinstance(dt, FloatType):
        return np.dtype(np.float64)
    if isinstance(dt, DecimalType):
        return np.dtype(np.int64)
    if isinstance(dt, DateType):
        return np.dtype(np.int32)
    if isinstance(dt, IntType):
        return np.dtype(np.int32 if dt.bits <= 32 else np.int64)
    raise TypeError(f"unbindable scalar param dtype {dt!r}")


def slot_name(e) -> str:
    if isinstance(e, ir.ParamRef):
        return f"p{e.index}"
    if isinstance(e, ir.DictParamIR):
        return f"d{e.index}"
    if isinstance(e, ir.InListParamIR):
        return f"v{e.index}"
    raise TypeError(f"not a param node: {e!r}")


def derive_dictionary(chain: tuple, tables: dict, table: str,
                      column: str) -> np.ndarray:
    """Replicate the device trace's dictionary transform chain on the
    host: the trace rewrites dictionaries with
    ``np.unique(transformed.astype(str))`` per step
    (device_exec._rewrite_dict/_eval_substr), so replaying the
    DictParamIR's chain spec (innermost-first) on the same base
    dictionary yields the same (sorted, deduped) final dictionary the
    compiled program's codes index."""
    col = tables[table].columns[column]
    if col.dictionary is None:
        raise ValueError(f"{table}.{column} is not dictionary-encoded")
    d = np.asarray(col.dictionary, dtype=object)
    for step in chain:
        vals = d.astype(str)
        if step[0] == "map":
            f = str.upper if step[1] == "upper" else str.lower
            out = np.array([f(s) for s in vals], dtype=object)
        elif step[0] == "concat":
            out = np.array([step[1] + s + step[2] for s in vals],
                           dtype=object)
        elif step[0] == "substr":
            lo = step[1] - 1
            hi = None if step[2] is None else lo + step[2]
            out = np.array([s[lo:hi] for s in vals], dtype=object)
        else:
            raise ValueError(f"unknown chain step {step!r}")
        d = np.unique(out.astype(str)).astype(object)
    return d


def _np_cmp(op, vals, lit):
    if op == "=":
        return vals == lit
    if op == "<>":
        return vals != lit
    if op == "<":
        return vals < lit
    if op == "<=":
        return vals <= lit
    if op == ">":
        return vals > lit
    if op == ">=":
        return vals >= lit
    raise ValueError(op)


def param_nodes(planned: P.PlannedQuery):
    """Every distinct parameter node in the plan (dict-keyed by slot:
    one hoisted literal appears exactly once by construction)."""
    out: dict = {}
    seen: set = set()
    for root in [planned.root, *planned.scalar_subplans]:
        for node in P.walk_plan(root):
            if id(node) in seen:
                continue
            seen.add(id(node))
            for e in P.all_exprs(node):
                if e is None:
                    continue
                for x in ir.walk(e):
                    if isinstance(x, (ir.ParamRef, ir.DictParamIR,
                                      ir.InListParamIR)):
                        out[slot_name(x)] = x
    return out


def bind_params(planned: P.PlannedQuery, tables: dict) -> dict:
    """slot -> host numpy value for one dispatch: scalars at their
    canonical dtypes, dictionary membership tables (negation applied in
    the traced program, NOT here — the table is canonical per value),
    and fixed-width IN-list vectors. Cheap by design: dictionary-sized
    numpy work, no row-count-sized work."""
    values = getattr(planned, "param_values", None)
    if not values:
        return {}
    from nds_tpu.engine.cpu_exec import like_mask
    out: dict = {}
    for slot, e in param_nodes(planned).items():
        if isinstance(e, ir.ParamRef):
            v = values[e.index]
            dt = scalar_np_dtype(e.dtype)
            if isinstance(e.dtype, DecimalType):
                # decimal literals are already plan-time scaled ints
                v = int(v)
            out[slot] = np.asarray(v, dtype=dt)
        elif isinstance(e, ir.InListParamIR):
            vals = list(values[e.index])
            dt = scalar_np_dtype(e.operand.dtype)
            if isinstance(e.operand.dtype, DecimalType):
                s = e.operand.dtype.scale
                vals = [int(round(float(x) * 10 ** s)) for x in vals]
            out[slot] = np.asarray(vals, dtype=dt)
        else:  # DictParamIR
            d = derive_dictionary(e.chain, tables, e.table, e.column)
            vals = d.astype(str)
            v = values[e.index]
            if e.kind == "like":
                table = like_mask(d, v)
            elif e.kind == "inlist":
                table = np.isin(vals, np.array([str(x) for x in v]))
            else:
                table = _np_cmp(e.op, vals, str(v))
            out[slot] = np.asarray(table, dtype=bool)
    return out
