"""SQL tokenizer for the TPC dialect subset."""

from __future__ import annotations

import re
from dataclasses import dataclass


@dataclass
class Token:
    kind: str    # ident|number|string|op|punct|eof
    value: str
    pos: int


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*\n?|/\*.*?\*/)
  | (?P<number>\d+\.\d+|\.\d+|\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*|`[^`]*`|"[^"]*")
  | (?P<op><=|>=|<>|!=|\|\||[=<>+\-*/%])
  | (?P<punct>[(),.;])
    """,
    re.VERBOSE | re.DOTALL,
)


class LexError(ValueError):
    pass


def tokenize(sql: str) -> list[Token]:
    tokens: list[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            snippet = sql[pos:pos + 20]
            raise LexError(f"unexpected character at {pos}: {snippet!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        value = m.group()
        if kind == "string":
            value = value[1:-1].replace("''", "'")
        elif kind == "ident":
            if value[0] in "`\"":
                value = value[1:-1]
        tokens.append(Token(kind, value, m.start()))
    tokens.append(Token("eof", "", n))
    return tokens
