"""Engine-wide metrics registry: counters, gauges, histograms.

The reference gets its operational counters (task retries, shuffle
spills, bytes read) from Spark's metrics system for free; this is the
in-process equivalent for the TPU engine.  One global registry, named
instruments created on first use, thread-safe behind a single lock
(instrument updates are query-granularity events, never per-row, so
one lock is cheaper than per-instrument locking everywhere).

Metric names in use across the stack (documented in README
"Observability"):

- ``queries_total`` / ``query_failures_total`` / ``query_seconds`` —
  power loop (utils/power_core.py)
- ``plans_total`` — SQL planner
- ``device_executions_total`` / ``compiles_total`` /
  ``recompiles_total`` / ``slack_retries_total`` /
  ``bytes_scanned_total`` — device executors
- ``staged_subprograms_total`` — host-staged plan splitting
- ``exchanges_traced_total`` / ``exchange_overflow_retries_total`` /
  ``exchange_overflow_rows_total`` — distributed exchange
- ``chunk_scans_total`` / ``chunk_fallbacks_total`` /
  ``chunk_shrink_total`` — out-of-core executor
- ``task_failures_total`` — TaskFailureCollector bridge
  (utils/report.py)
- ``faults_injected_total`` / ``query_retries_total`` /
  ``query_deadline_exceeded_total`` — resilience layer
  (nds_tpu/resilience/)
- ``query_reschedules_total`` / ``placement_consensus_total`` /
  ``placement_demotions_total`` / ``placement_promotions_total`` —
  unified execution pipeline (engine/scheduler.py)

Per-query deltas (``delta(before, after)``) land in each BenchReport
JSON under ``metrics``.
"""

from __future__ import annotations

from collections import deque

from nds_tpu.analysis import locksan


class Counter:
    """Monotonic accumulator (floats allowed: bytes_scanned_total)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock):
        self.name = name
        self.value = 0
        self._lock = lock

    def inc(self, n: int | float = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins value (e.g. live compile-cache entries)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock):
        self.name = name
        self.value = 0
        self._lock = lock

    def set(self, v: int | float) -> None:
        with self._lock:
            self.value = v


class Histogram:
    """count/sum/min/max plus p50/p95/p99 — latency distributions at
    query granularity without bucket-boundary bikeshedding. Quantiles
    come from a bounded window of the most recent observations (a
    99-query power run fits entirely; beyond that the tail quantiles
    track recent behavior, which is what a live snapshot wants)."""

    # recent-observation window the quantiles are computed over
    WINDOW = 2048

    __slots__ = ("name", "count", "sum", "min", "max", "_samples",
                 "_lock")

    def __init__(self, name: str, lock):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._samples: deque = deque(maxlen=self.WINDOW)
        self._lock = lock

    def observe(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self._samples.append(v)

    def _percentiles_locked(self) -> dict:
        s = sorted(self._samples)
        if not s:
            return {}
        n = len(s)
        return {f"p{q}": s[min(n - 1, max(0, (q * n + 99) // 100 - 1))]
                for q in (50, 95, 99)}

    def percentiles(self) -> dict:
        """Nearest-rank p50/p95/p99 over the recent-sample window
        ({} before the first observation)."""
        with self._lock:
            return self._percentiles_locked()

    def summary(self) -> dict:
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "min": self.min, "max": self.max,
                    **self._percentiles_locked()}


class MetricsRegistry:
    """One lock for the registry AND every instrument it creates —
    REENTRANT, so snapshot() can roll up instrument summaries while
    holding it and instruments can guard their own reads for direct
    callers. Instrument updates are query-granularity events, never
    per-row, so one shared lock stays cheaper than per-instrument
    locking everywhere."""

    def __init__(self) -> None:
        self._lock = locksan.rlock("obs.MetricsRegistry._lock")
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, self._lock)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, self._lock)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, self._lock)
            return h

    def snapshot(self) -> dict:
        """Point-in-time copy: {"counters": {...}, "gauges": {...},
        "histograms": {name: {count,sum,min,max}}}."""
        with self._lock:
            return {
                "counters": {n: c.value
                             for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "histograms": {n: h.summary()
                               for n, h in self._histograms.items()},
            }

    def reset(self) -> None:
        """Drop every instrument (tests)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


def delta(before: dict, after: dict) -> dict:
    """What changed between two snapshots, for per-query attribution:
    counter increments, histogram count/sum increments, current gauge
    values. Unchanged instruments are omitted."""
    out: dict = {}
    counters = {}
    for name, v in after.get("counters", {}).items():
        d = v - before.get("counters", {}).get(name, 0)
        if d:
            counters[name] = d
    if counters:
        out["counters"] = counters
    gauges = {
        name: v for name, v in after.get("gauges", {}).items()
        if before.get("gauges", {}).get(name) != v}
    if gauges:
        out["gauges"] = gauges
    hists = {}
    for name, h in after.get("histograms", {}).items():
        b = before.get("histograms", {}).get(
            name, {"count": 0, "sum": 0.0})
        dc = h["count"] - b["count"]
        if dc:
            entry = {"count": dc, "sum": h["sum"] - b["sum"]}
            # quantiles are distribution state, not increments: carry
            # the AFTER snapshot's values so each BenchReport shows the
            # latency distribution as of that query
            entry.update({k: h[k] for k in ("p50", "p95", "p99")
                          if k in h})
            hists[name] = entry
    if hists:
        out["histograms"] = hists
    return out


def labeled(name: str, **labels) -> str:
    """Instrument name carrying OpenMetrics-style labels:
    ``labeled("server_requests_total", tenant="a")`` ->
    ``server_requests_total{tenant="a"}``. The registry treats the
    whole string as the instrument key (one instrument per label set);
    the snapshot emitter (obs/snapshot.py) splits it back into family +
    labels when rendering the exposition."""
    if not labels:
        return name
    def esc(v) -> str:
        # OpenMetrics escaping (\\ then \"): distinct values must stay
        # distinct — deleting the metachars would collapse tenants
        # like 'acme' and 'acme"' onto one instrument
        return str(v).replace("\\", "\\\\").replace('"', '\\"')
    inner = ",".join(f'{k}="{esc(v)}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def split_labels(name: str) -> "tuple[str, str]":
    """(base_name, label_block) — label_block is '' or '{k="v",...}'."""
    i = name.find("{")
    if i < 0:
        return name, ""
    return name[:i], name[i:]


REGISTRY = MetricsRegistry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return REGISTRY.histogram(name)


def snapshot() -> dict:
    return REGISTRY.snapshot()
