"""Live metrics snapshot emitter: observable runs, not just post-mortems.

A 99-query power run or a 4-stream throughput round can hold the
terminal for an hour; until now the only signals were stdout lines and
the artifacts written AFTER the run.  ``NDS_TPU_METRICS_SNAP=
path[:interval]`` starts a daemon thread in the power loop that every
``interval`` seconds (default 5) writes the global metrics registry to:

- ``path`` — one JSON object (atomic tmp+rename, so a watcher never
  reads a torn file): ``{"ts", "progress", "counters", "gauges",
  "histograms"}`` plus ``"heartbeats"`` (per-unit ages from
  resilience/watchdog.py — what stream supervisors poll for liveness);
- the sibling OpenMetrics text file (``path`` with its extension
  replaced by ``.om``) — counter/gauge/summary families with
  ``nds_tpu_`` prefixes and a terminating ``# EOF``, scrapeable by
  anything Prometheus-shaped without new dependencies.

``progress`` is a caller-owned dict the power loop mutates in place
(current query, completed count), so the snapshot answers "where is it
and is it moving" — the two questions a stuck run raises first.  The
emitter is pure stdlib, failure-isolated (an unwritable path degrades
to a warning, never a query failure), and always writes one final
snapshot on ``stop()`` so short runs still leave a file.
"""

from __future__ import annotations

import os
import re
import threading
import time

SNAP_ENV = "NDS_TPU_METRICS_SNAP"
DEFAULT_INTERVAL_S = 5.0

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def parse_spec(spec: str) -> tuple[str, float]:
    """``path[:interval_s]`` -> (path, interval). A trailing segment
    that doesn't parse as a number is part of the path (Windows-style
    or exotic paths keep working)."""
    path, sep, tail = spec.rpartition(":")
    if sep:
        try:
            return path, max(0.05, float(tail))
        except ValueError:
            pass
    return spec, DEFAULT_INTERVAL_S


def om_path_for(json_path: str) -> str:
    root, ext = os.path.splitext(json_path)
    return (root if ext else json_path) + ".om"


def _metric_name(name: str) -> str:
    return "nds_tpu_" + _NAME_RE.sub("_", name)


def _split(name: str) -> tuple:
    """(sanitized family base, label block) for a possibly-labeled
    instrument name (obs/metrics.labeled): only the BASE sanitizes —
    the label block is emitted verbatim (values were escaped at
    labeling time)."""
    from nds_tpu.obs.metrics import split_labels
    base, labels = split_labels(name)
    return _metric_name(base), labels


def _merge_labels(labels: str, extra: str) -> str:
    """Join a label block with one extra ``k="v"`` pair."""
    if not labels:
        return "{" + extra + "}"
    return labels[:-1] + "," + extra + "}"


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def to_openmetrics(snap: dict) -> str:
    """Render one registry snapshot as OpenMetrics text: counters (the
    ``_total`` suffix moves from family name to sample name), gauges,
    and histograms as summary families (count/sum + quantile samples
    from the p50/p95/p99 window). Labeled instruments
    (obs/metrics.labeled — the serving layer's per-tenant counters and
    latency summaries) group under ONE ``# TYPE`` line per family with
    one sample per label set."""
    lines: list[str] = []
    typed: set = set()

    def declare(fam: str, kind: str) -> None:
        if fam not in typed:
            typed.add(fam)
            lines.append(f"# TYPE {fam} {kind}")

    for name, v in sorted(snap.get("counters", {}).items()):
        fam, labels = _split(name)
        fam = fam[:-len("_total")] if fam.endswith("_total") else fam
        declare(fam, "counter")
        lines.append(f"{fam}_total{labels} {_fmt(v)}")
    for name, v in sorted(snap.get("gauges", {}).items()):
        fam, labels = _split(name)
        declare(fam, "gauge")
        lines.append(f"{fam}{labels} {_fmt(v)}")
    for name, h in sorted(snap.get("histograms", {}).items()):
        fam, labels = _split(name)
        declare(fam, "summary")
        for q in ("p50", "p95", "p99"):
            if h.get(q) is not None:
                ql = _merge_labels(labels,
                                   f'quantile="0.{q[1:]}"')
                lines.append(f"{fam}{ql} {_fmt(h[q])}")
        lines.append(f"{fam}_count{labels} {_fmt(h.get('count', 0))}")
        lines.append(f"{fam}_sum{labels} {_fmt(h.get('sum', 0.0))}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


_LABEL_VAL = r"\"(?:[^\"\\]|\\.)*\""        # escaped per OpenMetrics
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"            # metric name
    r"(\{[a-zA-Z0-9_]+=" + _LABEL_VAL
    + r"(,[a-zA-Z0-9_]+=" + _LABEL_VAL + r")*\})?"
    r" -?[0-9][0-9eE.+-]*$")                # value


def validate_openmetrics(text: str) -> list[str]:
    """Schema errors for an OpenMetrics exposition ([] = valid): every
    line is a ``# TYPE``/``# HELP`` comment or a sample matching the
    declared families, and the document ends with ``# EOF``."""
    errors: list[str] = []
    lines = text.splitlines()
    if not lines or lines[-1] != "# EOF":
        errors.append("missing terminating '# EOF' line")
    families: set[str] = set()
    for i, line in enumerate(lines, 1):
        if not line:
            errors.append(f"line {i}: blank line")
            continue
        if line == "# EOF":
            if i != len(lines):
                errors.append(f"line {i}: '# EOF' before end of file")
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 3 or parts[1] not in ("TYPE", "HELP"):
                errors.append(f"line {i}: malformed comment {line!r}")
            elif parts[1] == "TYPE":
                families.add(parts[2])
            continue
        if not _SAMPLE_RE.match(line):
            errors.append(f"line {i}: malformed sample {line!r}")
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        base = name
        for suffix in ("_total", "_count", "_sum"):
            if name.endswith(suffix):
                base = name[:-len(suffix)]
                break
        if name not in families and base not in families:
            errors.append(f"line {i}: sample {name!r} has no # TYPE")
    return errors


class MetricsSnapshotter:
    """Daemon-thread periodic writer over the global registry."""

    def __init__(self, path: str, interval_s: float = DEFAULT_INTERVAL_S,
                 registry=None, progress: dict | None = None):
        from nds_tpu.obs import metrics as obs_metrics
        self.path = path
        self.interval_s = interval_s
        self.registry = registry or obs_metrics.REGISTRY
        self.progress = progress if progress is not None else {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._warned = False

    @classmethod
    def from_env(cls, progress: dict | None = None
                 ) -> "MetricsSnapshotter | None":
        spec = os.environ.get(SNAP_ENV)
        if not spec:
            return None
        path, interval = parse_spec(spec)
        return cls(path, interval, progress=progress)

    def write_once(self) -> None:
        snap = self.registry.snapshot()
        doc = {"ts": time.time(), "progress": dict(self.progress),
               **snap}
        # heartbeat ages (resilience/watchdog.py): the file mtime alone
        # is NOT liveness — this daemon keeps writing while the query
        # loop hangs; the embedded ages are what a supervisor watches
        from nds_tpu.resilience import watchdog
        hb = watchdog.snapshot_heartbeats()
        if hb:
            doc["heartbeats"] = hb
        # live HBM occupancy (obs/telemetry.py): latest reading + ring
        # depth; absent on no-stats backends so the snapshot keeps its
        # pre-telemetry shape there
        from nds_tpu.obs import telemetry
        tl = telemetry.snapshot_block()
        if tl:
            doc["telemetry"] = tl
        try:
            # pid+thread-unique tmps (write_json_atomic, and the same
            # scheme for the OpenMetrics sibling): two processes
            # pointed at one snapshot path (mis-threaded env) AND the
            # daemon thread racing a final stop() write each rename a
            # COMPLETE file into place, never interleave one tmp
            from nds_tpu.io.integrity import write_json_atomic
            write_json_atomic(self.path, doc)
            om = om_path_for(self.path)
            tmp = f"{om}.{os.getpid()}.{threading.get_ident()}.tmp"
            with open(tmp, "w") as f:
                f.write(to_openmetrics(snap))
            os.replace(tmp, om)
        except OSError as exc:
            if not self._warned:  # observability must not fail the run
                # ndsraces: waive[NDSR204] -- warn-once latch: a lost update costs at most one duplicate warning line
                self._warned = True
                print(f"[obs] metrics snapshot write failed: {exc}")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.write_once()

    def start(self) -> "MetricsSnapshotter":
        if self._thread is None:
            self.write_once()  # a file exists from t=0, not t=interval
            self._thread = threading.Thread(
                target=self._loop, name="nds-tpu-metrics-snap",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.write_once()  # final state always lands
