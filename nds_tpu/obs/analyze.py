"""Run analysis: time attribution, cross-run diff/regression gate, HTML.

PR 1 made every run EMIT spans and per-query metric deltas; nothing in
the repo consumed them — rounds were compared by eyeballing one scalar.
This module is the consumer.  It ingests a *run directory* (the
``json_summary_folder`` a power/throughput run writes: one BenchReport
JSON per query, plus any ``*.jsonl`` Chrome trace the run exported) and
produces three artifacts:

- **Time attribution** (``attribute_query``): each query's wall-clock
  decomposed over fixed categories — parse/plan, compile, device
  execute, materialize, host staging, exchange, retry backoff — by
  walking the span tree with *exclusive* (self-time) accounting: a
  span's self time bills to its own category, or to its nearest
  categorized ancestor (so a staged sub-program's dispatch overhead
  bills to host_staging, not nowhere).  Whatever no span covers lands
  in an explicit ``residual_ms``, so categories + residual sum to the
  reported wall-clock BY CONSTRUCTION — the breakdown can never
  quietly overlap or undercount ("Query Processing on Tensor
  Computation Runtimes" attributes TCR cost the same way: compile
  amortization vs steady-state must be separable or the numbers lie).
- **Cross-run diff + gate** (``diff_runs`` / ``diff_times``): compare
  two runs query-by-query on *steady-state* time (wall minus compile
  minus retry backoff), ignore sub-threshold absolute deltas as noise,
  flag compile-count changes separately, and report added/removed
  queries.  ``tools/ndsreport.py diff A B --gate pct=10`` exits
  non-zero on regression, so CI and future bench rounds gate on it.
- **HTML report** (``render_html``): self-contained stdlib HTML —
  per-query stacked attribution bars, slowest-N table, metrics, and a
  stream-overlap timeline from the trace JSONL for throughput runs.

No new dependencies; everything here is stdlib + the repo's own JSON
shapes (README "Observability" documents them; ``tools/
check_trace_schema.py --summary`` validates them).
"""

from __future__ import annotations

import html as _html
import json
import os

# attribution categories, in display order (retry_backoff comes from
# the summary's retry accounting, straggler_wait from cross-rank span
# pairing in fleet runs — both not from this rank's spans; prefetch_wait
# is the chunked engine's device-waited-on-host stall, carved out of
# the host_staging window by its own spans; residual is computed)
CATEGORIES = ("parse_plan", "compile", "execute", "materialize",
              "host_staging", "prefetch_wait", "exchange",
              "straggler_wait", "retry_backoff")

# span name -> category (exact names; see README span taxonomy)
_SPAN_CATEGORY = {
    "sql.parse": "parse_plan",
    "sql.plan": "parse_plan",
    "device.compile": "compile",
    "device.run": "execute",
    "device.materialize": "materialize",
    "stage.sub": "host_staging",
    "chunk.partial_agg": "host_staging",
    "chunk.reduce": "host_staging",
    "prefetch.wait": "prefetch_wait",
}

# summary files that live in run dirs but are not BenchReports
_IGNORE_BASENAMES = {"analysis.json", "bench_state.json"}


def span_category(name: str) -> str | None:
    cat = _SPAN_CATEGORY.get(name)
    if cat is None and name.startswith("exchange"):
        return "exchange"
    return cat


def is_report_basename(name: str) -> bool:
    """Whether a run-dir file name can be a BenchReport summary (the
    single place that decision lives — static_checks' fixture gate and
    load_summaries both use it). ``merged-*`` phase reports
    (utils/report.merge_incarnations) are DERIVED from the per-query
    summaries — ingesting them would double-bill every merged query —
    and ``*_queries.json`` files are resume journals
    (resilience/journal.QueryJournal), not reports."""
    return (name.endswith(".json") and name not in _IGNORE_BASENAMES
            and not name.startswith("merged-")
            and not name.endswith("_queries.json"))


# ---------------------------------------------------------- attribution

def _accumulate(node: dict, inherited: str | None, acc: dict) -> None:
    """Exclusive-time walk: each span's self time (dur minus direct
    children) bills to its own category, else to the nearest
    categorized ancestor, else nowhere (-> residual)."""
    cat = span_category(node.get("name", "")) or inherited
    kids = node.get("children") or []
    self_ms = (node.get("dur_ms") or 0.0) - sum(
        (k.get("dur_ms") or 0.0) for k in kids)
    if cat and self_ms > 0:
        acc[cat] += self_ms
    for k in kids:
        _accumulate(k, cat, acc)


def attribute_query(summary: dict) -> dict:
    """One BenchReport summary -> attribution row. Invariant:
    ``sum(categories.values()) + residual_ms == wall_ms`` exactly
    (residual is DEFINED as the difference — negative residual means
    span totals exceeded the bracket, a clock-skew signal worth seeing,
    not hiding)."""
    times = summary.get("queryTimes") or [0]
    wall_ms = float(times[-1])
    cats = {c: 0.0 for c in CATEGORIES}
    spans = summary.get("spans")
    if isinstance(spans, dict):
        _accumulate(spans, None, cats)
    cats["retry_backoff"] = float(
        summary.get("retry_backoff_s", 0.0)) * 1000.0
    counters = (summary.get("metrics") or {}).get("counters", {})
    status = summary.get("queryStatus") or ["Unknown"]
    row = {
        "query": summary.get("query", "?"),
        "status": status[-1],
        "start_time": summary.get("startTime"),
        "wall_ms": wall_ms,
        "categories": cats,
        "residual_ms": wall_ms - sum(cats.values()),
        "compiles": int(counters.get("compiles_total", 0)
                        + counters.get("recompiles_total", 0)),
        "retries": int(summary.get("retries", 0)),
    }
    mem = summary.get("memory")
    if isinstance(mem, dict) and "device_hwm_bytes" in mem:
        row["hwm_bytes"] = int(mem["device_hwm_bytes"])
    # scheduling decisions (engine/scheduler.py): which placement
    # served the query and how far the degradation ladder walked
    if "placement" in summary:
        row["placement"] = str(summary["placement"])
        row["reschedules"] = int(summary.get("reschedules", 0))
        if summary.get("ladder"):
            row["ladder"] = list(summary["ladder"])
        if summary.get("promoted_back"):
            row["promoted_back"] = True
    # plan-cache activity (nds_tpu/cache/; README "Plan cache"):
    # hits/misses per query — absent when no cache was active, so
    # pre-cache run dirs analyze byte-identically
    cache = summary.get("cache")
    if isinstance(cache, dict) and "hits" in cache:
        row["cache_hits"] = int(cache.get("hits", 0))
        row["cache_misses"] = int(cache.get("misses", 0))
    # kernel use + roofline model (engine/kernels.py; README "Kernels
    # & roofline"): which relational kernels the compiled program ran
    # with, and the query's arithmetic intensity / bandwidth fraction
    if isinstance(summary.get("kernels"), dict):
        row["kernels"] = {str(k): int(v)
                          for k, v in summary["kernels"].items()}
    et = summary.get("engineTimings") or {}
    for k in ("ops_per_byte", "roofline_frac"):
        if isinstance(et.get(k), (int, float)):
            row[k] = float(et[k])
    # columnar compression (nds_tpu/columnar/): encoded bytes the
    # query actually scanned, plus the ratio vs raw when the
    # compressed store was active (absent rows keep pre-columnar run
    # dirs analyzing byte-identically)
    for k in ("bytes_scanned", "compression_ratio"):
        if isinstance(et.get(k), (int, float)):
            row[k] = float(et[k])
    # writable-warehouse deltas (nds_tpu/columnar/delta.py): how many
    # append-only segments and masked (deleted) rows rode under the
    # tables this query scanned. Absent on delta-free runs, so
    # pre-maintenance run dirs keep analyzing byte-identically
    for k in ("delta_segments", "delta_appended_rows",
              "delta_masked_rows"):
        if isinstance(et.get(k), (int, float)):
            row[k] = int(et[k])
    # pipelined execution (engine/pipeline_io.py): host staging time
    # the prefetch overlapped under compute, and the derived device
    # occupancy (1 - prefetch_wait/wall — what fraction of the query's
    # wall the device was NOT stalled on host staging). Absent on
    # pre-pipeline runs, so old dirs keep analyzing byte-identically
    if isinstance(et.get("prefetch_hidden_s"), (int, float)):
        row["prefetch_hidden_s"] = float(et["prefetch_hidden_s"])
    if cats["prefetch_wait"] > 0 or "prefetch_hidden_s" in row:
        row["occupancy"] = (round(1.0 - cats["prefetch_wait"] / wall_ms,
                                  4) if wall_ms > 0 else 1.0)
    # on-demand XLA capture (obs/profile.py; README "Fleet &
    # profiling"): which trigger fired and where the capture landed
    prof = summary.get("profile")
    if isinstance(prof, dict) and prof.get("path"):
        row["profile"] = {"trigger": str(prof.get("trigger", "query")),
                          "path": str(prof["path"])}
    # compiler-truth cost ledger (obs/costs.py): the query's summed
    # XLA flops/bytes, the roofline-model predicted time against the
    # recorded platform's peaks, and the achieved fraction (predicted
    # over measured execute — how close the run came to the model's
    # ceiling). Absent on pre-cost run dirs, which keep analyzing
    # byte-identically
    cost = summary.get("cost")
    if isinstance(cost, dict) and isinstance(cost.get("programs"),
                                             dict):
        row["cost"] = dict(cost)
        from nds_tpu.obs import costs as _costs
        pred = _costs.predicted_ms(cost)
        if pred is not None:
            row["predicted_ms"] = round(pred, 3)
            measured = (cats["execute"] if cats["execute"] > 0
                        else wall_ms - cats["compile"]
                        - cats["retry_backoff"])
            if measured > 0:
                row["achieved_frac"] = round(pred / measured, 4)
    # HBM occupancy telemetry (obs/telemetry.py): series shape summary
    tl = summary.get("telemetry")
    if isinstance(tl, dict) and tl.get("samples"):
        row["telemetry_samples"] = int(tl["samples"])
        hbm = tl.get("hbm") or {}
        if isinstance(hbm.get("max_bytes"), (int, float)):
            row["hbm_max_bytes"] = int(hbm["max_bytes"])
    return row


def _quantiles(samples: list) -> dict:
    """Nearest-rank p50/p95/p99 over a sample list ({} when empty) —
    the serving layer's per-tenant latency summary."""
    s = sorted(samples)
    if not s:
        return {}
    n = len(s)
    return {f"p{q}": round(
        s[min(n - 1, max(0, (q * n + 99) // 100 - 1))], 3)
        for q in (50, 95, 99)}


def steady_ms(row: dict) -> float:
    """Steady-state time: wall minus compile minus retry backoff — the
    quantity the regression gate compares (compile-count changes are
    flagged separately; a run that merely recompiled more is a
    different finding than one whose execution got slower)."""
    return (row["wall_ms"] - row["categories"]["compile"]
            - row["categories"]["retry_backoff"])


# ------------------------------------------------------------ ingestion

def load_summaries(run_dir: str) -> list[dict]:
    """Every BenchReport JSON under ``run_dir`` (recursive), in
    startTime order. Non-report JSONs (journals, analysis output,
    unparseable files) are skipped silently — run dirs are shared."""
    out = []
    for root, _dirs, files in os.walk(run_dir):
        for fname in sorted(files):
            if not is_report_basename(fname):
                continue
            try:
                with open(os.path.join(root, fname)) as f:
                    obj = json.load(f)
            except (OSError, ValueError):
                continue
            if (isinstance(obj, dict) and "queryStatus" in obj
                    and "query" in obj):
                out.append(obj)
    out.sort(key=lambda s: (s.get("startTime") or 0))
    return out


def load_trace_events(run_dir: str,
                      fleet_meta: "list[dict] | None" = None
                      ) -> list[dict]:
    """All Chrome trace events from ``*.jsonl`` files under
    ``run_dir`` (the power loop's NDS_TPU_TRACE export). When the run
    dir carries fleet sidecars (``fleet-r<rank>.json``, obs/fleet.py),
    each rank shard's timestamps are CLOCK-ALIGNED onto rank 0's
    timeline by subtracting that rank's handshake offset — the merge
    that makes one fleet timeline out of per-host clocks."""
    offsets_us: dict[str, float] = {}
    for meta in fleet_meta or []:
        shard = meta.get("trace_shard")
        off = meta.get("boot_offset_s")
        if shard and meta.get("aligned") and off:
            offsets_us[str(shard)] = float(off) * 1e6
    events = []
    for root, _dirs, files in os.walk(run_dir):
        for fname in sorted(files):
            if not fname.endswith(".jsonl"):
                continue
            shift = offsets_us.get(fname, 0.0)
            try:
                with open(os.path.join(root, fname)) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(ev, dict) and ev.get("ph") == "X":
                            if shift and isinstance(ev.get("ts"),
                                                    (int, float)):
                                ev["ts"] = ev["ts"] - shift
                            events.append(ev)
            except OSError:
                continue
    return events


# ------------------------------------------------------ fleet stragglers

def straggler_stats(events: list[dict]) -> dict:
    """Cross-rank pairing of per-query spans in a clock-aligned fleet
    trace: for every query that ran on 2+ ranks (pid = rank, the
    obs/fleet export contract), pair each rank's ARRIVAL at the
    executor (its first ``device.execute`` event inside the query
    span; the query span start as fallback) and derive the straggler
    shape: the collective program cannot complete anywhere before the
    LAST rank arrives, so per-rank wait = last_arrival - own_arrival,
    the slowest rank is the last to arrive, and the skew is the full
    arrive spread. Returns ``{query: {"wait_ms_by_rank": {rank: ms},
    "slowest_rank", "skew_ms"}}`` — queries appearing more than once
    on a rank are skipped (pairing instances across ranks would be
    guesswork)."""
    by_rank_q: dict = {}
    dev_by_rank: dict = {}
    for ev in events:
        if not isinstance(ev.get("ts"), (int, float)):
            continue
        if ev.get("name") == "query":
            q = (ev.get("args") or {}).get("query")
            if q:
                by_rank_q.setdefault(ev.get("pid"), {}).setdefault(
                    str(q), []).append(ev)
        elif ev.get("name") == "device.execute":
            dev_by_rank.setdefault(ev.get("pid"), []).append(ev)
    out: dict = {}
    queries = set()
    for qmap in by_rank_q.values():
        queries.update(qmap)
    for q in queries:
        arrivals: dict = {}
        for rank, qmap in by_rank_q.items():
            evs = qmap.get(q) or []
            if len(evs) != 1:
                continue
            ev = evs[0]
            t0, t1 = ev["ts"], ev["ts"] + ev.get("dur", 0.0)
            inside = [d["ts"] for d in dev_by_rank.get(rank, [])
                      if t0 <= d["ts"] <= t1]
            arrivals[rank] = min(inside) if inside else t0
        if len(arrivals) < 2:
            continue
        last = max(arrivals.values())
        slowest = max(arrivals, key=lambda r: arrivals[r])
        out[q] = {
            "wait_ms_by_rank": {r: round((last - t) / 1000.0, 3)
                                for r, t in arrivals.items()},
            "slowest_rank": slowest,
            "skew_ms": round((last - min(arrivals.values())) / 1000.0,
                             3),
        }
    return out


def merge_resumed(summaries: list[dict]) -> "tuple[list[dict], dict]":
    """Bill merged incarnations once: a resumed run
    (utils/power_core ``--resume``) can report the same query from two
    incarnations — the first process died in the window between
    writing the summary and appending the journal, and the resumed
    incarnation re-ran it. Keep the LATEST (incarnation, startTime)
    report per query, so totals/diffs never double-count; returns
    (summaries, {query: dropped_count}). Runs that never resumed
    (every ``incarnation`` is 0 or absent — including multi-stream
    throughput dirs, whose repeated names are legitimate separate
    executions) pass through untouched."""
    if not any((s.get("incarnation") or 0) > 0 for s in summaries
               if isinstance(s.get("incarnation"), int)):
        return summaries, {}
    out: list = []
    best: dict = {}
    dropped: dict = {}
    for s in summaries:
        if not isinstance(s.get("incarnation"), int):
            out.append(s)  # not journal-stamped: leave it alone
            continue
        q = str(s.get("query"))
        key = (s["incarnation"], s.get("startTime") or 0)
        cur = best.get(q)
        if cur is None:
            best[q] = (key, s)
        else:
            dropped[q] = dropped.get(q, 0) + 1
            if key > cur[0]:
                best[q] = (key, s)
    out.extend(s for _k, s in best.values())
    out.sort(key=lambda s: (s.get("startTime") or 0))
    return out, dropped


def _dedupe_names(rows: list[dict]) -> None:
    """Throughput dirs repeat query names across streams; suffix
    repeats (#2, #3...) so per-name maps stay lossless. Suffixes are
    assigned by wall-clock RANK, not arrival order: stream-scheduling
    jitter must not re-label instances between two runs, or diff_runs
    would pair mismatched instances and report phantom regressions —
    rank pairing compares fastest-to-fastest, slowest-to-slowest."""
    groups: dict[str, list] = {}
    for row in rows:
        groups.setdefault(row["query"], []).append(row)
    for name, g in groups.items():
        if len(g) > 1:
            ranked = sorted(g, key=lambda r: (r["wall_ms"],
                                              r["start_time"] or 0))
            for i, row in enumerate(ranked[1:], 2):
                row["query"] = f"{name}#{i}"


def analyze_run(run_dir: str, with_trace: bool = True) -> dict:
    """Full run analysis: attribution rows, category totals, slowest-N,
    run-level metric aggregates, and trace events for the timeline.
    ``with_trace=False`` skips parsing the (potentially huge) trace
    JSONL — the diff gate only needs the BenchReport-derived rows
    (fleet dirs then also skip straggler attribution, which needs the
    merged shards)."""
    summaries = load_summaries(run_dir)
    if not summaries:
        raise ValueError(f"no BenchReport JSONs under {run_dir!r}")
    # resumed runs: bill each merged-incarnation query exactly once
    # (the same latest-incarnation-wins rule the merged phase report
    # applies, utils/report.merge_incarnations)
    summaries, merged_dropped = merge_resumed(summaries)
    rows = [attribute_query(s) for s in summaries]
    _dedupe_names(rows)
    # fleet runs (obs/fleet.py sidecars): merge the per-rank shards
    # onto one clock-aligned timeline and re-bill the recording rank's
    # execute time that was really WAITING on the slowest rank into
    # the straggler_wait category. The move is execute -> straggler,
    # so categories + residual still sum to wall-clock by construction
    from nds_tpu.obs import fleet as _fleet
    fleet_meta = _fleet.load_fleet(run_dir)
    events = (load_trace_events(run_dir, fleet_meta) if with_trace
              else [])
    fleet_info = None
    if fleet_meta:
        fleet_info = {
            "world": max(m.get("world", 1) for m in fleet_meta),
            "ranks": [{k: m.get(k) for k in
                       ("rank", "host", "pid", "boot_offset_s",
                        "aligned", "trace_shard")}
                      for m in fleet_meta],
        }
    if (fleet_info and fleet_info["world"] > 1 and events
            and all(m.get("aligned") for m in fleet_meta)):
        # an unaligned fleet (failed handshake) still merges, but
        # arrival pairing against skewed clocks would invent
        # stragglers — attribution needs the aligned timeline
        strag = straggler_stats(events)
        # summaries come from the primary (rank 0) recorder: its wait
        # on the fleet's slowest rank is what re-bills
        for row in rows:
            s = strag.get(row["query"])
            if not s:
                continue
            wait = float(s["wait_ms_by_rank"].get(0, 0.0))
            wait = max(0.0, min(wait, row["categories"]["execute"]))
            row["categories"]["straggler_wait"] = wait
            row["categories"]["execute"] -= wait
            row["straggler"] = {"skew_ms": s["skew_ms"],
                                "slowest_rank": s["slowest_rank"]}
    totals = {c: 0.0 for c in CATEGORIES}
    residual = 0.0
    for row in rows:
        for c in CATEGORIES:
            totals[c] += row["categories"][c]
        residual += row["residual_ms"]
    counters: dict = {}
    hists: dict = {}
    for s in summaries:
        m = s.get("metrics") or {}
        for name, v in m.get("counters", {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, h in m.get("histograms", {}).items():
            agg = hists.setdefault(name, {"count": 0, "sum": 0.0})
            agg["count"] += h.get("count", 0)
            agg["sum"] += h.get("sum", 0.0)
            # quantiles are point-in-time: keep the latest reported
            agg.update({k: h[k] for k in ("p50", "p95", "p99")
                        if k in h})
    out = {
        "run_dir": os.path.abspath(run_dir),
        "queries": rows,
        "totals": {"wall_ms": sum(r["wall_ms"] for r in rows),
                   "categories": totals, "residual_ms": residual},
        "slowest": [r["query"] for r in sorted(
            rows, key=lambda r: -r["wall_ms"])],
        "failed": [r["query"] for r in rows
                   if r["status"] != "Completed"],
        "metrics": {"counters": counters, "histograms": hists},
        "trace_events": events,
    }
    # serving runs (nds_tpu/serve/): per-tenant request latency
    # quantiles over the per-request summaries' wall clocks
    tenant_walls: dict = {}
    for s in summaries:
        t = s.get("tenant")
        if t and s.get("queryTimes"):
            tenant_walls.setdefault(t, []).append(
                float(s["queryTimes"][-1]))
    if tenant_walls:
        out["tenants"] = {
            t: {"requests": len(walls),
                **{f"{q}_ms": v
                   for q, v in _quantiles(walls).items()}}
            for t, walls in sorted(tenant_walls.items())}
    # fleet serving runs (nds_tpu/serve/fleet.py): the same rollup
    # keyed by replica, plus divergence flagging — one replica whose
    # tail is far off the fleet's is a sick member (thermal, noisy
    # neighbor, wedged cache), not a workload property
    replica_walls: dict = {}
    for s in summaries:
        rep = s.get("replica")
        if rep and s.get("queryTimes"):
            replica_walls.setdefault(rep, []).append(
                float(s["queryTimes"][-1]))
    if replica_walls:
        reps = {
            rep: {"requests": len(walls),
                  **{f"{q}_ms": v
                     for q, v in _quantiles(walls).items()}}
            for rep, walls in sorted(replica_walls.items())}
        p99s = sorted(d["p99_ms"] for d in reps.values())
        fleet_median_p99 = p99s[len(p99s) // 2]
        for d in reps.values():
            if fleet_median_p99 > 0 and (
                    d["p99_ms"] > 2.0 * fleet_median_p99):
                d["outlier"] = True
        out["replicas"] = reps
        out["fleet_median_p99_ms"] = fleet_median_p99
    # banked/stale metrics must never flow silently into analysis
    # consumers (ROADMAP item 2): surface the marker loudly; ndsreport
    # diff refuses to gate on it
    stale = [s.get("query") or s.get("filename", "?")
             for s in summaries if s.get("stale_device_times")]
    if stale:
        out["stale_device_times"] = stale
    if merged_dropped:
        out["merged_incarnations"] = merged_dropped
    incs = [s.get("incarnation") for s in summaries
            if isinstance(s.get("incarnation"), int)]
    if incs and max(incs) > 0:
        out["incarnations"] = max(incs) + 1
    if fleet_info:
        out["fleet"] = fleet_info
    return out


# ------------------------------------------------------------- CLI text

def format_attribution(analysis: dict, top: int | None = None) -> str:
    """Fixed-width per-query attribution table (the ``ndsreport
    analyze`` stdout contract): categories + residual per query, sum
    column provably equal to wall-clock."""
    short = {"parse_plan": "parse", "compile": "compile",
             "execute": "exec", "materialize": "mat",
             "host_staging": "stage", "prefetch_wait": "pfwait",
             "exchange": "exch", "straggler_wait": "stragl",
             "retry_backoff": "retry"}
    rows = analysis["queries"]
    if top:
        order = {q: i for i, q in enumerate(analysis["slowest"])}
        rows = sorted(rows, key=lambda r: order[r["query"]])[:top]
    w = max([len(r["query"]) for r in rows] + [5])
    has_placement = any("placement" in r for r in rows)
    has_cache = any("cache_hits" in r for r in rows)
    has_roofline = any("ops_per_byte" in r or "roofline_frac" in r
                       for r in rows)
    has_bytes = any("bytes_scanned" in r for r in rows)
    has_delta = any("delta_segments" in r for r in rows)
    has_profile = any("profile" in r for r in rows)
    has_occup = any("occupancy" in r for r in rows)
    has_cost = any("cost" in r for r in rows)
    cols = list(CATEGORIES) + ["residual", "wall"]
    head = (f"{'query':<{w}} " + " ".join(
        f"{short.get(c, c):>9}" for c in cols)
        + ("  placement" if has_placement else "")
        + ("  cache" if has_cache else "")
        + ("   roofline" if has_roofline else "")
        + ("         bytes" if has_bytes else "")
        + ("        delta" if has_delta else "")
        + ("  occup" if has_occup else "")
        + ("  predicted  achieved" if has_cost else "")
        + ("  profile" if has_profile else "") + "  status")
    lines = [head, "-" * len(head)]
    for r in rows:
        vals = [r["categories"][c] for c in CATEGORIES]
        vals += [r["residual_ms"], r["wall_ms"]]
        place = ""
        if has_placement:
            p = r.get("placement", "?")
            if r.get("reschedules"):
                p += f"(+{r['reschedules']})"
            place = f"  {p:>9}"
        cache_col = ""
        if has_cache:
            if "cache_hits" in r:
                # hit when every consult hit; miss when any compile
                # fell through; "err" when the block exists with zero
                # consults (fingerprint failure — attach_cache only
                # emits an all-zero block when errors moved); "-" for
                # queries the cache never saw
                hits, misses = r["cache_hits"], r["cache_misses"]
                verdict = ("err" if not hits and not misses else
                           "hit" if misses == 0 else
                           "miss" if hits == 0 else "part")
            else:
                verdict = "-"
            cache_col = f"  {verdict:>5}"
        roof_col = ""
        if has_roofline:
            # "<ops/byte>@<bandwidth fraction>": distance from the
            # roofline — a LOW ops/byte at a LOW fraction means the
            # query moves bytes it barely computes on (README "Kernels
            # & roofline" reads this column)
            ob = r.get("ops_per_byte")
            rf = r.get("roofline_frac")
            cell = ("-" if ob is None and rf is None else
                    (f"{ob:.2f}" if ob is not None else "?")
                    + "@"
                    + (f"{rf * 100.0:.0f}%" if rf is not None else "?"))
            roof_col = f"  {cell:>9}"
        bytes_col = ""
        if has_bytes:
            # encoded scan bytes + compression ratio ("1.9M x5.0"):
            # how much the columnar store shrank this query's HBM
            # traffic (README "Compressed columnar store")
            bs = r.get("bytes_scanned")
            cell = "-" if bs is None else _fmt_bytes(bs)
            cr = r.get("compression_ratio")
            if cr is not None:
                cell += f" x{cr:.1f}"
            bytes_col = f"  {cell:>12}"
        delta_col = ""
        if has_delta:
            # delta state under the query's scanned tables:
            # "<segments>s +<appended> -<masked>" — a nonzero cell
            # means the query ran over a mutated warehouse without a
            # re-encode (README "Writable warehouse")
            if "delta_segments" in r:
                cell = (f"{r['delta_segments']}s "
                        f"+{r.get('delta_appended_rows', 0)} "
                        f"-{r.get('delta_masked_rows', 0)}")
            else:
                cell = "-"
            delta_col = f"  {cell:>12}"
        occup_col = ""
        if has_occup:
            # device occupancy under pipelined execution: 100% means
            # the device never waited on host chunk staging (README
            # "Pipelined execution")
            occ = r.get("occupancy")
            occup_col = ("  {:>5}".format(
                f"{occ * 100.0:.0f}%" if occ is not None else "-"))
        cost_col = ""
        if has_cost:
            # compiler-truth roofline model: predicted execute time
            # (flops/bytes against the platform's peaks) and the
            # achieved fraction of that ceiling — a LOW fraction means
            # the query left the modeled hardware idle (README "Cost
            # ledger & telemetry")
            pm = r.get("predicted_ms")
            af = r.get("achieved_frac")
            cost_col = ("  {:>9}  {:>8}".format(
                f"{pm:.1f}ms" if pm is not None else "-",
                f"{af * 100.0:.0f}%" if af is not None else "-"))
        prof_col = ""
        if has_profile:
            prof_col = ("  {:>7}".format(
                r["profile"]["trigger"] if "profile" in r else "-"))
        lines.append(
            f"{r['query']:<{w}} "
            + " ".join(f"{v:>9.1f}" for v in vals)
            + place + cache_col + roof_col + bytes_col + delta_col
            + occup_col + cost_col + prof_col + f"  {r['status']}")
    t = analysis["totals"]
    tvals = [t["categories"][c] for c in CATEGORIES]
    tvals += [t["residual_ms"], t["wall_ms"]]
    lines.append("-" * len(head))
    lines.append(f"{'TOTAL':<{w}} "
                 + " ".join(f"{v:>9.1f}" for v in tvals) + "  (ms)")
    if analysis.get("incarnations"):
        note = f"resumed run: {analysis['incarnations']} incarnations"
        md = analysis.get("merged_incarnations")
        if md:
            note += (", merged (billed once): "
                     + ", ".join(f"{q} (x{n + 1})"
                                 for q, n in sorted(md.items())))
        lines.append(note)
    fl = analysis.get("fleet")
    if fl:
        ranks = ", ".join(
            f"r{r.get('rank')}@{r.get('host')}"
            f"{'' if r.get('aligned') else ' (UNALIGNED)'}"
            for r in fl.get("ranks", []))
        lines.append(f"fleet: {fl.get('world')} rank(s): {ranks}")
        # ALL rows, not the top-N slice: the worst-skew query need
        # not be among the slowest by wall-clock
        blamed = [(r["query"], r["straggler"])
                  for r in analysis["queries"]
                  if r.get("straggler")]
        for q, s in sorted(blamed,
                           key=lambda e: -e[1]["skew_ms"])[:5]:
            lines.append(f"  straggler {q}: rank "
                         f"{s['slowest_rank']} arrived last "
                         f"(skew {s['skew_ms']:.1f} ms)")
    return "\n".join(lines)


# ------------------------------------------------------------ diff/gate

def parse_gate(spec: str | None) -> dict:
    """``pct=10`` / ``pct=10,abs_ms=50`` -> thresholds dict.  A delta
    must exceed BOTH the relative and the absolute floor to count —
    that's the noise model (sub-threshold absolute wobble on fast
    queries must not fail a gate). ``cost_pct`` is the COST-DRIFT
    threshold: compiler flops/bytes for an unchanged query moving by
    more than this fails the gate even when wall-clock noise hides
    the regression (compiler numbers are deterministic — their noise
    floor is ~0, so the default can be generous and still be a
    tripwire)."""
    gate = {"pct": 10.0, "abs_ms": 50.0, "cost_pct": 25.0}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        if key not in gate:
            raise ValueError(f"unknown gate key {key!r} "
                             f"(known: {sorted(gate)})")
        gate[key] = float(val)
    return gate


def diff_times(base: dict, cur: dict, pct: float = 10.0,
               abs_ms: float = 50.0) -> dict:
    """Core noise-aware comparison over two {name: ms} maps (the same
    code path gates fixture run-dirs in CI and the round bench's
    per-query block). Regression: cur exceeds base by BOTH >pct% and
    >=abs_ms. Symmetric for improvements; everything else is noise."""
    regressions, improvements, noise = [], [], []
    for name in sorted(set(base) & set(cur)):
        b, c = float(base[name]), float(cur[name])
        d = c - b
        entry = {"query": name, "base_ms": round(b, 3),
                 "cur_ms": round(c, 3), "delta_ms": round(d, 3),
                 "pct": round(d / b * 100.0, 2) if b > 0 else None}
        # a zero/negative baseline (clock-skew steady-state, zeroed
        # BASELINE entry) makes the relative test vacuous: any growth
        # past the absolute floor is then a regression, not noise
        if d >= abs_ms and (b <= 0 or c > b * (1 + pct / 100.0)):
            regressions.append(entry)
        elif -d >= abs_ms and b > 0 and c < b * (1 - pct / 100.0):
            improvements.append(entry)
        else:
            noise.append(entry)
    regressions.sort(
        key=lambda e: -(e["pct"] if e["pct"] is not None
                        else float("inf")))
    improvements.sort(key=lambda e: (e["pct"] or 0))
    return {
        "regressions": regressions,
        "improvements": improvements,
        "noise": noise,
        "added": sorted(set(cur) - set(base)),
        "removed": sorted(set(base) - set(cur)),
        "gate": {"pct": pct, "abs_ms": abs_ms},
    }


# the slow-path kernels (engine/kernels.py catalog): a per-query
# increase in these counts between runs is a DEMOTION — the planner
# (or a feasibility check) silently dropped the query off the fast
# kernels — and fails the diff gate like a removed query does
SLOW_KERNELS = ("join.sortmerge", "semi.sortmerge", "agg.scatter")


def _slow_uses(row: dict) -> int:
    kern = row.get("kernels") or {}
    return sum(int(kern.get(k, 0)) for k in SLOW_KERNELS)


def kernel_changes(base_rows: dict, cur_rows: dict) -> list:
    """Per-query kernel-choice changes between two runs (the same
    mechanism as the compile-count flag): any difference in the
    ``kernels`` block is reported; entries whose slow-path use COUNT
    grew carry ``demoted: True`` and fail the gate. Queries with no
    kernel block on either side (pre-kernel run dirs) are skipped, so
    old fixtures keep diffing byte-identically — and a side MISSING
    the block entirely (a baseline recorded before the kernel layer
    existed) is flagged as a change but never as a demotion: the gate
    must not hard-fail the first diff across the feature boundary
    when the absent counts merely read as zero."""
    out = []
    for name in sorted(set(base_rows) & set(cur_rows)):
        b, c = base_rows[name], cur_rows[name]
        bk, ck = b.get("kernels"), c.get("kernels")
        if bk is None and ck is None:
            continue
        if bk == ck:
            continue
        entry = {"query": name, "base": bk or {}, "cur": ck or {}}
        if (bk is not None and ck is not None
                and _slow_uses(c) > _slow_uses(b)):
            entry["demoted"] = True
        out.append(entry)
    return out


# absolute floor for the bytes_scanned gate: sub-MiB wobble (a reduced
# scan view flipping on a borderline survivor count) is noise, a MiB+
# growth is a real bandwidth regression
BYTES_ABS_FLOOR = 1 << 20


def bytes_changes(base_rows: dict, cur_rows: dict,
                  pct: float = 10.0) -> list:
    """Per-query ``bytes_scanned`` changes between two runs, gated the
    same way steady-state time is: a query whose scanned bytes grew by
    BOTH >pct% and >=1 MiB carries ``regressed: True`` and fails the
    diff — the engine is bandwidth-bound, so silently re-inflating the
    scan working set (an encoding demoted to raw, a reduced view lost)
    is a perf regression even when the fixture machine hid the time.
    Queries without the field on either side (pre-columnar run dirs)
    are skipped; a side MISSING it entirely is flagged but never
    fails the gate (first diff across the feature boundary)."""
    out = []
    for name in sorted(set(base_rows) & set(cur_rows)):
        b = base_rows[name].get("bytes_scanned")
        c = cur_rows[name].get("bytes_scanned")
        if b is None and c is None:
            continue
        if b == c:
            continue
        entry = {"query": name, "base_bytes": b, "cur_bytes": c}
        if (b is not None and c is not None
                and c - b >= BYTES_ABS_FLOOR
                and c > b * (1 + pct / 100.0)):
            entry["regressed"] = True
        out.append(entry)
    return out


# occupancy-regression threshold: the prefetch_wait SHARE of a query's
# wall rising by more than this many points between runs means the
# pipeline stopped hiding host staging (a lost overlap, a depth
# demotion gone sticky, a stage function that got slower) — flagged
# PIPELINE-STALLED and failed like a kernel demotion
STALL_SHARE_POINTS = 0.10


def _prefetch_share(row: dict) -> float:
    wall = row.get("wall_ms") or 0.0
    if wall <= 0:
        return 0.0
    return (row.get("categories", {}).get("prefetch_wait", 0.0)
            or 0.0) / wall


def pipeline_changes(base_rows: dict, cur_rows: dict) -> list:
    """Per-query prefetch-stall changes between two runs: entries only
    for queries where a side actually carried pipeline evidence
    (nonzero ``prefetch_wait`` or a ``prefetch_hidden_s`` field), so
    pre-pipeline run dirs keep diffing byte-identically. A query whose
    ``prefetch_wait`` share of wall-clock ROSE by more than
    ``STALL_SHARE_POINTS`` carries ``stalled: True`` and fails the
    gate."""
    out = []

    def _evidence(r) -> bool:
        return _prefetch_share(r) > 0 or "prefetch_hidden_s" in r

    for name in sorted(set(base_rows) & set(cur_rows)):
        b, c = base_rows[name], cur_rows[name]
        if not _evidence(b) and not _evidence(c):
            continue
        bs, cs = _prefetch_share(b), _prefetch_share(c)
        if abs(cs - bs) < 0.01:
            continue
        entry = {"query": name, "base_share": round(bs, 4),
                 "cur_share": round(cs, 4)}
        # the feature boundary never hard-fails (the kernel_changes /
        # bytes_changes precedent): a base recorded pre-pipeline — or
        # with prefetch off — has no occupancy claim to regress from
        if _evidence(b) and cs - bs > STALL_SHARE_POINTS:
            entry["stalled"] = True
        out.append(entry)
    return out


# absolute floor for the compiler-flops drift gate: a megaflop of
# movement on a tiny query is a constant-folding wobble, not a plan
# change worth failing CI over
FLOPS_ABS_FLOOR = 1e6


def cost_changes(base_rows: dict, cur_rows: dict,
                 pct: float = 25.0) -> list:
    """Per-query compiler-cost drift between two runs: entries for
    queries whose ``cost`` block flops or bytes_accessed moved, with
    ``drifted: True`` (gate failure) when either moved by BOTH >pct%
    and >= the absolute floor in EITHER direction — compiler numbers
    are deterministic for an unchanged query, so a swing either way
    means the compiled program changed, even when wall-clock noise
    hides it. Queries without the block on either side (pre-cost run
    dirs) are skipped; a side MISSING it entirely is flagged but
    never fails the gate (the kernel_changes / bytes_changes
    feature-boundary precedent)."""
    out = []
    for name in sorted(set(base_rows) & set(cur_rows)):
        b = base_rows[name].get("cost")
        c = cur_rows[name].get("cost")
        if b is None and c is None:
            continue
        moved = False
        drifted = False
        entry: dict = {"query": name}
        for key, floor in (("flops", FLOPS_ABS_FLOOR),
                           ("bytes_accessed", BYTES_ABS_FLOOR)):
            bv = (b or {}).get(key)
            cv = (c or {}).get(key)
            if bv == cv:
                continue
            moved = True
            entry[f"base_{key}"] = bv
            entry[f"cur_{key}"] = cv
            if (b is not None and c is not None
                    and isinstance(bv, (int, float))
                    and isinstance(cv, (int, float))
                    and abs(cv - bv) >= floor
                    and bv > 0
                    and abs(cv - bv) / bv > pct / 100.0):
                drifted = True
        if b is None or c is None:
            entry["missing"] = "base" if b is None else "cur"
            out.append(entry)
            continue
        if not moved:
            continue
        if drifted:
            entry["drifted"] = True
        out.append(entry)
    return out


def cache_hit_rate(analysis: dict) -> "dict | None":
    """Run-level plan-cache summary from the per-query rows:
    ``{"hits", "misses", "rate"}`` (rate = hits / consults), or None
    when no query carried a cache block (cache off — pre-cache run
    dirs keep diffing byte-identically)."""
    hits = misses = 0
    seen = False
    for r in analysis.get("queries", []):
        if "cache_hits" in r:
            seen = True
            hits += r["cache_hits"]
            misses += r["cache_misses"]
    if not seen:
        return None
    total = hits + misses
    return {"hits": hits, "misses": misses,
            "rate": round(hits / total, 4) if total else None}


# the 11 maintenance refresh functions (nds/maintenance.py INSERT/
# DELETE/INVENTORY_DELETE_FUNCS — listed literally: this module stays
# importable without the engine stack). Their per-function BenchReport
# summaries land in run dirs like query reports do, but they are DML:
# the steady-state decomposition doesn't apply, so they diff on FULL
# refresh wall-clock under their own MAINT-REGRESSED gate — the TPC
# metric charges Tdm for exactly this time
MAINT_FUNCS = frozenset((
    "LF_CR", "LF_CS", "LF_I", "LF_SR", "LF_SS", "LF_WR", "LF_WS",
    "DF_CS", "DF_SS", "DF_WS", "DF_I"))


def _is_maint_fn(name: str) -> bool:
    return name.partition("#")[0] in MAINT_FUNCS


def maint_changes(base_rows: dict, cur_rows: dict, pct: float = 10.0,
                  abs_ms: float = 50.0) -> list:
    """Per-function refresh-time changes between two runs holding
    maintenance summaries: the same noise model as steady-state time
    but over FULL wall-clock (DML has no compile/steady split worth
    separating), with ``regressed: True`` failing the gate. A function
    present in base but MISSING from cur also fails — a refresh
    function that vanished is strictly worse than one that got slower.
    Runs with no maintenance summaries on either side emit nothing, so
    query-only run dirs keep diffing byte-identically."""
    b = {q: r["wall_ms"] for q, r in base_rows.items()
         if _is_maint_fn(q)}
    c = {q: r["wall_ms"] for q, r in cur_rows.items()
         if _is_maint_fn(q)}
    if not b and not c:
        return []
    d = diff_times(b, c, pct=pct, abs_ms=abs_ms)
    out = []
    for e in d["regressions"]:
        out.append({**e, "regressed": True})
    out += d["improvements"]
    for q in d["removed"]:
        out.append({"query": q, "removed": True, "regressed": True})
    for q in d["added"]:
        out.append({"query": q, "added": True})
    return out


def diff_runs(base: dict, cur: dict, pct: float = 10.0,
              abs_ms: float = 50.0, cost_pct: float = 25.0) -> dict:
    """Query-by-query diff of two ``analyze_run`` results, gated on
    STEADY-STATE time; compile-count and compile-time changes are
    reported in their own ``compile_changes`` list so a recompile
    shows up as what it is, not as an execution regression.  The gate
    fails (``passed=False``) on any steady-state regression or any
    removed query (a query that vanished is strictly worse than one
    that got slower)."""
    b_rows = {r["query"]: r for r in base["queries"]}
    c_rows = {r["query"]: r for r in cur["queries"]}
    # maintenance refresh functions gate on their own wall-clock
    # (MAINT-REGRESSED) and leave the query-side comparisons — a
    # refresh summary has no kernels/bytes/cost surface to diff
    mchanges = maint_changes(b_rows, c_rows, pct=pct, abs_ms=abs_ms)
    maint_regressed = [e["query"] for e in mchanges
                       if e.get("regressed")]
    b_rows = {q: r for q, r in b_rows.items() if not _is_maint_fn(q)}
    c_rows = {q: r for q, r in c_rows.items() if not _is_maint_fn(q)}
    d = diff_times({q: steady_ms(r) for q, r in b_rows.items()},
                   {q: steady_ms(r) for q, r in c_rows.items()},
                   pct=pct, abs_ms=abs_ms)
    compile_changes = []
    for name in sorted(set(b_rows) & set(c_rows)):
        b, c = b_rows[name], c_rows[name]
        if (b["compiles"] != c["compiles"]
                or abs(b["categories"]["compile"]
                       - c["categories"]["compile"]) >= abs_ms):
            compile_changes.append({
                "query": name,
                "base_compiles": b["compiles"],
                "cur_compiles": c["compiles"],
                "base_compile_ms": round(b["categories"]["compile"], 3),
                "cur_compile_ms": round(c["categories"]["compile"], 3),
            })
    newly_failed = sorted(
        set(cur.get("failed", [])) - set(base.get("failed", [])))
    # kernel-choice changes (engine/kernels.py): flagged like compile
    # counts; a slow-path DEMOTION fails the gate — a planner
    # regression that quietly re-sorts q21 must not pass just because
    # the fixture machine was fast that day
    kchanges = kernel_changes(b_rows, c_rows)
    demoted = [e["query"] for e in kchanges if e.get("demoted")]
    # bytes_scanned regressions gate like steady-state time: the
    # roofline says these queries are bandwidth-bound, so scanned
    # bytes ARE a perf surface (README "Compressed columnar store")
    bchanges = bytes_changes(b_rows, c_rows, pct=pct)
    bytes_regressed = [e["query"] for e in bchanges
                       if e.get("regressed")]
    # occupancy regressions (engine/pipeline_io.py): a prefetch_wait
    # share rising >STALL_SHARE_POINTS means the pipeline stopped
    # hiding host staging — PIPELINE-STALLED fails the gate; run dirs
    # with no pipeline evidence on either side emit nothing here
    pchanges = pipeline_changes(b_rows, c_rows)
    stalled = [e["query"] for e in pchanges if e.get("stalled")]
    # compiler-cost drift (obs/costs.py): deterministic flops/bytes
    # moving >cost_pct for an unchanged query is a plan/program change
    # — COST-DRIFT fails the gate even when wall-clock noise hides it
    cchanges = cost_changes(b_rows, c_rows, pct=cost_pct)
    cost_drifted = [e["query"] for e in cchanges if e.get("drifted")]
    d["gate"]["cost_pct"] = cost_pct
    d.update({
        "base_dir": base.get("run_dir"),
        "cur_dir": cur.get("run_dir"),
        "compile_changes": compile_changes,
        "kernel_changes": kchanges,
        "bytes_changes": bchanges,
        "pipeline_changes": pchanges,
        "cost_changes": cchanges,
        "maint_changes": mchanges,
        "newly_failed": newly_failed,
        "passed": not d["regressions"] and not d["removed"]
                  and not newly_failed and not demoted
                  and not bytes_regressed and not stalled
                  and not cost_drifted and not maint_regressed,
    })
    # plan-cache hit-rate per run, the compile-count-change flag's
    # natural companion: a run whose compile counts dropped to 0
    # should show a warm cache explaining WHY (README "Plan cache").
    # Only when a side actually carried a cache block — pre-cache run
    # dirs keep diffing byte-identically
    chr_base, chr_cur = cache_hit_rate(base), cache_hit_rate(cur)
    if chr_base is not None or chr_cur is not None:
        d["cache_hit_rate"] = {"base": chr_base, "cur": chr_cur}
    # banked/stale device times are not comparable evidence: a diff
    # over them must FAIL loudly (ROADMAP item 2 — the BENCH_r04/r05
    # rot class), never gate-pass on numbers nobody measured this run
    stale = {side: a["stale_device_times"]
             for side, a in (("base", base), ("cur", cur))
             if a.get("stale_device_times")}
    if stale:
        d["stale_device_times"] = stale
        d["passed"] = False
    return d


def format_diff(d: dict) -> str:
    lines = [f"gate: >{d['gate']['pct']:g}% and "
             f">={d['gate']['abs_ms']:g} ms (steady-state)"]
    for label, key, sign in (("REGRESSION", "regressions", "+"),
                             ("improvement", "improvements", "")):
        for e in d[key]:
            rel = ("n/a" if e["pct"] is None
                   else f"{sign}{e['pct']:g}%")
            lines.append(
                f"  {label:<11} {e['query']:<14} "
                f"{e['base_ms']:>10.1f} -> {e['cur_ms']:>10.1f} ms "
                f"({rel})")
    for q in d["removed"]:
        lines.append(f"  REMOVED     {q}")
    for q in d.get("newly_failed", []):
        lines.append(f"  NEWLY-FAILED {q}")
    for q in d["added"]:
        lines.append(f"  added       {q}")
    for e in d["compile_changes"]:
        lines.append(
            f"  compile     {e['query']:<14} "
            f"{e['base_compiles']} compile(s)/"
            f"{e['base_compile_ms']:.0f} ms -> {e['cur_compiles']}/"
            f"{e['cur_compile_ms']:.0f} ms")
    for e in d.get("kernel_changes", []):
        def _mix(kern):
            return ",".join(f"{k}x{v}" for k, v in sorted(kern.items())) \
                or "none"
        label = "KERNEL-DEMOTED" if e.get("demoted") else "kernel"
        lines.append(
            f"  {label:<11} {e['query']:<14} "
            f"{_mix(e['base'])} -> {_mix(e['cur'])}")
    for e in d.get("bytes_changes", []):
        # widest label in this block is BYTES-REGRESSED (15): pad the
        # whole block to it so flagged rows don't shear the columns
        label = "BYTES-REGRESSED" if e.get("regressed") else "bytes"
        def _b(v):
            return "-" if v is None else _fmt_bytes(v)
        lines.append(
            f"  {label:<15} {e['query']:<14} "
            f"{_b(e['base_bytes'])} -> {_b(e['cur_bytes'])}")
    for e in d.get("pipeline_changes", []):
        # occupancy regression: the device's prefetch_wait share of
        # wall rose — the overlap stopped hiding host staging
        label = "PIPELINE-STALLED" if e.get("stalled") else "pipeline"
        lines.append(
            f"  {label:<16} {e['query']:<14} "
            f"stall share {e['base_share'] * 100.0:.0f}% -> "
            f"{e['cur_share'] * 100.0:.0f}%")
    for e in d.get("cost_changes", []):
        # compiler-cost drift: deterministic flops/bytes moved for an
        # unchanged query — the compiled program itself changed
        label = "COST-DRIFT" if e.get("drifted") else "cost"
        if e.get("missing"):
            lines.append(f"  {label:<11} {e['query']:<14} "
                         f"cost block missing on {e['missing']} side")
            continue
        parts = []
        for key, fmt in (("flops", "{:.3g}"),
                         ("bytes_accessed", None)):
            if f"base_{key}" in e or f"cur_{key}" in e:
                def _v(v, _fmt=fmt):
                    if v is None:
                        return "-"
                    return (_fmt.format(v) if _fmt
                            else _fmt_bytes(v))
                parts.append(f"{key} {_v(e.get(f'base_{key}'))} -> "
                             f"{_v(e.get(f'cur_{key}'))}")
        lines.append(f"  {label:<11} {e['query']:<14} "
                     + "; ".join(parts))
    for e in d.get("maint_changes", []):
        # per-function refresh wall-clock (the Tdm the TPC metric
        # charges): a regression here is a write-path slowdown even
        # when every query held steady
        label = "MAINT-REGRESSED" if e.get("regressed") else "maint"
        if e.get("removed"):
            lines.append(f"  {label:<15} {e['query']:<14} "
                         f"refresh function missing from cur run")
        elif e.get("added"):
            lines.append(f"  {label:<15} {e['query']:<14} "
                         f"refresh function new in cur run")
        else:
            rel = ("n/a" if e["pct"] is None else f"{e['pct']:+g}%")
            lines.append(
                f"  {label:<15} {e['query']:<14} "
                f"{e['base_ms']:>10.1f} -> {e['cur_ms']:>10.1f} ms "
                f"({rel})")
    chr_ = d.get("cache_hit_rate") or {}
    if any(chr_.get(k) for k in ("base", "cur")):
        def _rate(r):
            if not r:
                return "off"
            if r["rate"] is None:
                return "0 consults"
            return (f"{r['rate'] * 100.0:.0f}% "
                    f"({r['hits']}/{r['hits'] + r['misses']})")
        lines.append(f"  cache       hit-rate "
                     f"{_rate(chr_.get('base'))} -> "
                     f"{_rate(chr_.get('cur'))}")
    lines.append(f"  {len(d['noise'])} querie(s) within noise threshold")
    for side, names in d.get("stale_device_times", {}).items():
        lines.append(f"  STALE       {side}: banked device times "
                     f"({len(names)} summar"
                     f"{'y' if len(names) == 1 else 'ies'}) — not "
                     f"comparable evidence")
    lines.append("DIFF " + ("OK" if d["passed"] else "FAILED"))
    return "\n".join(lines)


# ----------------------------------------------------------------- HTML

# categorical slots (documented default palette, fixed order — the
# 7-slot adjacent sequence passes the CVD/normal-vision gates in both
# modes per the palette doc); residual wears neutral gray, not a
# series hue
_LIGHT = {"parse_plan": "#2a78d6", "compile": "#eb6834",
          "execute": "#1baf7a", "materialize": "#eda100",
          "host_staging": "#e87ba4", "prefetch_wait": "#0e8a9e",
          "exchange": "#008300",
          "straggler_wait": "#8a6d3b", "retry_backoff": "#4a3aa7",
          "residual": "#b9b8b3"}
_DARK = {"parse_plan": "#3987e5", "compile": "#d95926",
         "execute": "#199e70", "materialize": "#c98500",
         "host_staging": "#d55181", "prefetch_wait": "#23a9bf",
         "exchange": "#008300",
         "straggler_wait": "#b0905a", "retry_backoff": "#9085e9",
         "residual": "#6e6d69"}

_CSS = """
:root { color-scheme: light dark; }
body { font: 13px/1.45 system-ui, sans-serif; margin: 24px;
       background: #fcfcfb; color: #0b0b0b; }
h1 { font-size: 18px; } h2 { font-size: 15px; margin-top: 28px; }
table { border-collapse: collapse; margin: 8px 0; }
th, td { padding: 3px 10px; text-align: right;
         border-bottom: 1px solid #e4e3df; }
th { color: #52514e; font-weight: 600; }
td.q, th.q { text-align: left; font-family: ui-monospace, monospace; }
.bar { display: flex; width: 620px; height: 14px; gap: 2px; }
.bar span { display: block; height: 100%; border-radius: 3px;
            min-width: 0; }
.legend { display: flex; gap: 16px; flex-wrap: wrap; margin: 8px 0;
          color: #52514e; }
.legend i { display: inline-block; width: 10px; height: 10px;
            border-radius: 3px; margin-right: 5px; }
.lane { position: relative; height: 18px; margin: 3px 0;
        background: #f0efec; border-radius: 3px; }
.lane b { position: absolute; top: 2px; bottom: 2px;
          border-radius: 3px; opacity: 0.9; }
.muted { color: #52514e; }
%LIGHT%
@media (prefers-color-scheme: dark) {
  body { background: #1a1a19; color: #ffffff; }
  th { color: #c3c2b7; } th, td { border-color: #383835; }
  .legend { color: #c3c2b7; } .lane { background: #242423; }
  .muted { color: #c3c2b7; }
  %DARK%
}
"""


def _css_vars() -> str:
    light = " ".join(f".c-{k} {{ background: {v}; }}"
                     for k, v in _LIGHT.items())
    dark = " ".join(f".c-{k} {{ background: {v}; }}"
                    for k, v in _DARK.items())
    return _CSS.replace("%LIGHT%", light).replace("%DARK%", dark)


def _esc(s) -> str:
    return _html.escape(str(s))


def _bar(row: dict) -> str:
    wall = max(row["wall_ms"], 1e-9)
    segs = []
    parts = list(row["categories"].items())
    parts.append(("residual", max(row["residual_ms"], 0.0)))
    for cat, ms in parts:
        if ms <= 0:
            continue
        pct = 100.0 * ms / wall
        segs.append(
            f'<span class="c-{cat}" style="width:{pct:.2f}%" '
            f'title="{_esc(row["query"])} {cat}: {ms:.1f} ms '
            f'({pct:.1f}%)"></span>')
    return f'<div class="bar">{"".join(segs)}</div>'


def _legend() -> str:
    items = "".join(
        f'<span><i class="c-{c}"></i>{c}</span>'
        for c in list(CATEGORIES) + ["residual"])
    return f'<div class="legend">{items}</div>'


def _fmt_bytes(n) -> str:
    if n is None:
        return ""
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.0f} {unit}" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024


def _timeline(events: list[dict],
              fleet: "dict | None" = None) -> str:
    """Stream-overlap timeline: one lane per (pid, tid), one bar per
    root ``query`` event — concurrency (throughput streams) is visible
    as vertical overlap. Single-lane power runs render too (a gap map
    is still informative). Fleet runs (obs/fleet.py: pid = rank,
    shards clock-aligned at load) label each lane with its rank, so
    the per-rank lanes read as the fleet timeline."""
    qevents = [e for e in events if e.get("name") == "query"
               and isinstance(e.get("ts"), (int, float))]
    if not qevents:
        return ""
    ranks = {r.get("rank") for r in (fleet or {}).get("ranks", [])}
    t0 = min(e["ts"] for e in qevents)
    t1 = max(e["ts"] + e.get("dur", 0) for e in qevents)
    span_us = max(t1 - t0, 1.0)
    lanes: dict = {}
    for e in qevents:
        lanes.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    rows = []
    for i, (lane, evs) in enumerate(sorted(
            lanes.items(), key=lambda kv: (str(kv[0][0]),
                                           str(kv[0][1]))), 1):
        bars = "".join(
            f'<b class="c-execute" '
            f'style="left:{100.0 * (e["ts"] - t0) / span_us:.2f}%;'
            f'width:{max(100.0 * e.get("dur", 0) / span_us, 0.15):.2f}%"'
            f' title="{_esc(e.get("args", {}).get("query", "?"))}'
            f' {e.get("dur", 0) / 1000.0:.1f} ms"></b>'
            for e in sorted(evs, key=lambda e: e["ts"]))
        label = (f"rank {lane[0]}" if lane[0] in ranks
                 else f"stream {i}")
        rows.append(
            f'<div class="lane" title="{_esc(label)}">{bars}</div>')
    title = ("Fleet timeline (clock-aligned)" if ranks
             else "Stream overlap timeline")
    return (f"<h2>{title}</h2>"
            f'<p class="muted">{len(lanes)} lane(s), '
            f"{span_us / 1e6:.2f} s span; hover a bar for the query."
            f"</p>{''.join(rows)}")


def render_html(analysis: dict, diff: dict | None = None,
                top: int = 10) -> str:
    """Self-contained report (no external assets, stdlib only)."""
    t = analysis["totals"]
    out = [
        "<!DOCTYPE html><html><head><meta charset='utf-8'>",
        "<title>ndsreport</title>",
        f"<style>{_css_vars()}</style></head><body>",
        f"<h1>Run analysis — {_esc(analysis['run_dir'])}</h1>",
        f"<p class='muted'>{len(analysis['queries'])} quer(ies), "
        f"{t['wall_ms'] / 1000.0:.2f} s total wall-clock, "
        f"{len(analysis['failed'])} failed</p>",
    ]
    fleet = analysis.get("fleet")
    if fleet:
        ranks = ", ".join(
            f"rank {r.get('rank')} @ {_esc(r.get('host'))} "
            f"(offset {r.get('boot_offset_s', 0):+.3f} s"
            f"{'' if r.get('aligned') else ', UNALIGNED'})"
            for r in fleet.get("ranks", []))
        out.append(f"<p class='muted'>fleet: {fleet.get('world')} "
                   f"rank(s) — {ranks}</p>")
    out += [
        "<h2>Per-query time attribution</h2>", _legend(),
        "<table><tr><th class='q'>query</th><th>wall ms</th>"
        "<th>breakdown</th><th>residual ms</th><th>compiles</th>"
        "<th>cache</th><th>retries</th><th>placement</th>"
        "<th>kernels</th><th>roofline</th><th>bytes</th>"
        "<th>predicted</th><th>achieved</th>"
        "<th>straggler</th><th>profile</th>"
        "<th>mem HWM</th><th>status</th></tr>",
    ]
    for row in analysis["queries"]:
        place = row.get("placement", "")
        if row.get("ladder"):
            # the walked ladder is the interesting story: show the
            # whole path, not only where the query landed
            place = "&rarr;".join(_esc(r) for r in row["ladder"])
        elif place:
            place = _esc(place)
        if row.get("promoted_back"):
            place += " &uarr;"
        if "cache_hits" in row:
            cache = (f"{row['cache_hits']} hit / "
                     f"{row['cache_misses']} miss")
        else:
            cache = ""
        kern = ", ".join(
            f"{_esc(k)}&times;{v}"
            for k, v in sorted((row.get("kernels") or {}).items()))
        ob, rf = row.get("ops_per_byte"), row.get("roofline_frac")
        roof = ""
        if ob is not None or rf is not None:
            roof = ((f"{ob:.2f}" if ob is not None else "?") + " @ "
                    + (f"{rf * 100.0:.0f}%" if rf is not None else "?"))
        # encoded scan bytes + compression ratio (nds_tpu/columnar/)
        bcell = ""
        if row.get("bytes_scanned") is not None:
            bcell = _fmt_bytes(row["bytes_scanned"])
            if row.get("compression_ratio") is not None:
                bcell += f" &times;{row['compression_ratio']:.1f}"
        strag = ""
        if row.get("straggler"):
            s = row["straggler"]
            strag = (f"rank {_esc(s['slowest_rank'])} "
                     f"(+{s['skew_ms']:.1f} ms)")
        prof = ""
        if row.get("profile"):
            p = row["profile"]
            prof = (f"<span title='{_esc(p['path'])}'>"
                    f"{_esc(p['trigger'])}</span>")
        # predicted-vs-measured (obs/costs roofline model): blank on
        # pre-cost rows and on platforms without a peaks entry
        pred = ("" if row.get("predicted_ms") is None
                else f"{row['predicted_ms']:.1f} ms")
        ach = ("" if row.get("achieved_frac") is None
               else f"{row['achieved_frac'] * 100.0:.0f}%")
        out.append(
            f"<tr><td class='q'>{_esc(row['query'])}</td>"
            f"<td>{row['wall_ms']:.1f}</td><td>{_bar(row)}</td>"
            f"<td>{row['residual_ms']:.1f}</td>"
            f"<td>{row['compiles']}</td><td>{cache}</td>"
            f"<td>{row['retries']}</td>"
            f"<td>{place}</td>"
            f"<td class='q'>{kern}</td><td>{roof}</td>"
            f"<td>{bcell}</td>"
            f"<td>{pred}</td><td>{ach}</td>"
            f"<td>{strag}</td><td>{prof}</td>"
            f"<td>{_fmt_bytes(row.get('hwm_bytes'))}</td>"
            f"<td>{_esc(row['status'])}</td></tr>")
    out.append("</table>")
    out.append(f"<h2>Slowest {min(top, len(analysis['queries']))}</h2>")
    out.append("<table><tr><th class='q'>query</th><th>wall ms</th>"
               "<th>steady ms</th><th>compile ms</th></tr>")
    by_name = {r["query"]: r for r in analysis["queries"]}
    for q in analysis["slowest"][:top]:
        r = by_name[q]
        out.append(f"<tr><td class='q'>{_esc(q)}</td>"
                   f"<td>{r['wall_ms']:.1f}</td>"
                   f"<td>{steady_ms(r):.1f}</td>"
                   f"<td>{r['categories']['compile']:.1f}</td></tr>")
    out.append("</table>")
    if diff:
        out.append("<h2>Diff vs "
                   f"{_esc(diff.get('base_dir') or 'baseline')}</h2>")
        out.append(f"<pre>{_esc(format_diff(diff))}</pre>")
    m = analysis["metrics"]
    if m["counters"] or m["histograms"]:
        out.append("<h2>Metrics</h2>")
        out.append("<table><tr><th class='q'>counter</th>"
                   "<th>total</th></tr>")
        for name, v in sorted(m["counters"].items()):
            out.append(f"<tr><td class='q'>{_esc(name)}</td>"
                       f"<td>{v:g}</td></tr>")
        out.append("</table>")
        if m["histograms"]:
            out.append("<table><tr><th class='q'>histogram</th>"
                       "<th>count</th><th>sum</th><th>p50</th>"
                       "<th>p95</th><th>p99</th></tr>")
            for name, h in sorted(m["histograms"].items()):
                cells = "".join(
                    f"<td>{h.get(k):g}</td>" if h.get(k) is not None
                    else "<td></td>"
                    for k in ("count", "sum", "p50", "p95", "p99"))
                out.append(f"<tr><td class='q'>{_esc(name)}</td>"
                           f"{cells}</tr>")
            out.append("</table>")
    out.append(_timeline(analysis["trace_events"],
                         analysis.get("fleet")))
    out.append("</body></html>")
    return "".join(out)


# ------------------------------------------------------------ artifacts

def write_outputs(analysis: dict, out_dir: str,
                  diff: dict | None = None) -> dict:
    """Persist ``analysis.json`` + ``report.html`` into ``out_dir``;
    returns {kind: path}. Trace events stay out of the JSON (they are
    already on disk next to it)."""
    os.makedirs(out_dir, exist_ok=True)
    doc = {k: v for k, v in analysis.items() if k != "trace_events"}
    if diff:
        doc["diff"] = diff
    paths = {"analysis": os.path.join(out_dir, "analysis.json"),
             "report": os.path.join(out_dir, "report.html")}
    # atomic (NDS109): live dashboards poll analysis.json while runs
    # re-analyze; a torn read must be impossible
    from nds_tpu.io.integrity import write_json_atomic
    write_json_atomic(paths["analysis"], doc)
    # pid-suffixed tmp, same as write_json_atomic: two analyzers
    # re-analyzing one run dir must each rename a COMPLETE file
    tmp = f"{paths['report']}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(render_html(analysis, diff))
    os.replace(tmp, paths["report"])
    return paths
