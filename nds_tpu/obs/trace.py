"""Span-based query tracer: where the time goes, per query, per phase.

The reference harness delegates all timing depth to the Spark UI /
event logs; this engine's only accounting used to be a mutable
``last_timings`` dict scraped off the executor after the fact.  This
module is the replacement contract: every pipeline phase (parse, plan,
compile, execute, materialize, staged sub-programs, chunk scans) runs
inside a *span* — a named wall-clock bracket with attributes, nestable
into a per-query tree.  Spans bracket ``block_until_ready`` boundaries
upstream (the utils/report.py contract), so async dispatch cannot hide
work.

Design constraints, in order:

- **Zero-cost when disabled.** ``NDS_TPU_OBS=0`` makes ``span()`` /
  ``begin()`` return one shared no-op object; no allocation, no clock
  read, no lock.
- **Thread/executor-safe.** The "current span" is thread-local; async
  executors carry their span explicitly (``begin`` + ``attach``)
  instead of relying on stack discipline that interleaved queries
  would break.
- **Export is a side effect of finishing a root.** When a root span
  (no parent) ends, its whole tree appends to the Chrome trace-event
  JSONL named by ``NDS_TPU_TRACE`` (one JSON object per line, "X"
  complete events — Perfetto-loadable after wrapping in ``[...]``, see
  README "Observability"), and the root is retained on
  ``Tracer.last_roots`` for the BenchReport JSON.

The span taxonomy and the event schema are documented in the README
and enforced by ``tools/check_trace_schema.py``.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque

from nds_tpu.analysis import locksan

TRACE_ENV = "NDS_TPU_TRACE"
_OBS_ENV = "NDS_TPU_OBS"

# perf_counter -> epoch calibration, done once: Chrome trace "ts" wants
# one consistent microsecond timeline, perf_counter wants to be the
# only clock spans ever read
_EPOCH_OFFSET = time.time() - time.perf_counter()

_EXPORT_LOCK = locksan.lock("obs.trace._EXPORT_LOCK")

# deterministic export identity (obs/fleet.py): multi-process fleets
# export with pid=rank and supervised throughput streams with
# pid=stream index, so merged Chrome traces get stable, collision-free
# lanes instead of OS pids that can collide across hosts (and are
# arbitrary between runs). None = the legacy os.getpid() default.
_EXPORT_PID: int | None = None
# thread ident -> small stable lane id (1 = first exporting thread,
# usually main): Chrome/Perfetto lanes stay readable and two shards
# merged into one timeline cannot alias each other's giant pthread ids
_TID_MAP: dict[int, int] = {}


def set_export_pid(pid: int | None) -> None:
    """Pin the pid every exported event carries (rank in a fleet,
    stream index in a subprocess throughput fleet). ``None`` restores
    the os.getpid() default."""
    global _EXPORT_PID
    _EXPORT_PID = None if pid is None else int(pid)


def export_pid() -> int:
    return os.getpid() if _EXPORT_PID is None else _EXPORT_PID


def _compact_tid(ident: int) -> int:
    tid = _TID_MAP.get(ident)
    if tid is None:
        with _EXPORT_LOCK:
            tid = _TID_MAP.setdefault(ident, len(_TID_MAP) + 1)
    return tid


def epoch_offset() -> float:
    """The perf_counter->epoch calibration exported ``ts`` values use —
    the clock basis the fleet clock handshake (obs/fleet.py) must
    measure, or per-rank offsets would correct a different clock than
    the one stamping the events."""
    return _EPOCH_OFFSET


def _shift_epoch_offset(seconds: float) -> None:
    """TEST HOOK: skew this process's export clock by ``seconds`` —
    how the fleet-merge tests simulate two hosts with disagreeing
    wall clocks without touching the host clock."""
    global _EPOCH_OFFSET
    _EPOCH_OFFSET += seconds

# begin() default-parent sentinel: "whatever span is current on this
# thread" (None must stay expressible as "force a root")
_CURRENT = object()


class Span:
    """One named wall-clock bracket. Usable as a context manager (sync
    code: nests via the tracer's thread-local stack) or via explicit
    ``begin``/``end`` (async executors that outlive their dispatch
    thread turn)."""

    __slots__ = ("name", "attrs", "parent", "children", "t0", "t1",
                 "tid", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, parent: "Span | None",
                 attrs: dict, t0: float | None = None):
        self.name = name
        self.attrs = attrs
        self.parent = parent
        self.children: list[Span] = []
        self.t0 = time.perf_counter() if t0 is None else t0
        self.t1: float | None = None
        self.tid = threading.get_ident()
        self._tracer = tracer
        if parent is not None:
            parent.children.append(self)

    def __bool__(self) -> bool:
        return True

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def end(self, t: float | None = None) -> "Span":
        """Close the bracket (idempotent). ``t`` overrides the end
        timestamp for phases whose start/stop were measured by the
        caller's own perf_counter reads."""
        if self.t1 is None:
            self.t1 = time.perf_counter() if t is None else t
            if self.parent is None:
                self._tracer._finish_root(self)
        return self

    @property
    def dur_ms(self) -> float:
        end = self.t1 if self.t1 is not None else time.perf_counter()
        return (end - self.t0) * 1000.0

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()

    def find(self, name: str) -> "list[Span]":
        return [s for s in self.walk() if s.name == name]

    # ------------------------------------------------------- conversions

    def to_dict(self) -> dict:
        """JSON-ready tree for the BenchReport ``spans`` field."""
        return {
            "name": self.name,
            "dur_ms": round(self.dur_ms, 3),
            "attrs": _json_safe(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }

    def to_events(self, pid: int | None = None) -> list[dict]:
        """Chrome trace-event dicts ("X" complete events) for this span
        and every descendant. ``pid`` defaults to the process's export
        identity (``set_export_pid`` — rank in a fleet, stream index in
        a throughput fleet, os.getpid() otherwise); tids are compact
        per-process lane ids, not raw pthread idents, so merged
        multi-shard traces never alias lanes."""
        pid = export_pid() if pid is None else pid
        out = []
        for s in self.walk():
            out.append({
                "name": s.name,
                "cat": s.name.split(".", 1)[0],
                "ph": "X",
                "ts": (s.t0 + _EPOCH_OFFSET) * 1e6,
                "dur": s.dur_ms * 1000.0,
                "pid": pid,
                "tid": _compact_tid(s.tid),
                "args": _json_safe(s.attrs),
            })
        return out

    def __enter__(self) -> "Span":
        self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._tracer._pop(self)
        if exc is not None:
            self.attrs.setdefault("error", f"{type(exc).__name__}: {exc}")
        self.end()


class _NoopSpan:
    """Shared do-nothing span: the entire disabled-mode cost is one
    attribute load and a falsy check."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def set(self, **attrs) -> "_NoopSpan":
        return self

    def end(self, t=None) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        return None


NOOP_SPAN = _NoopSpan()


def _json_safe(obj):
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


class _Attach:
    """Context manager that makes an explicitly-owned span the
    thread-local current span WITHOUT ending it on exit (the async
    executors' bridge between begin/end ownership and ``with span``
    nesting for everything called underneath)."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span):
        self._tracer = tracer
        self._span = span

    def __enter__(self):
        if self._span:
            self._tracer._push(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._span:
            self._tracer._pop(self._span)


class Tracer:
    """Owns the thread-local span stack, finished-root retention, and
    the Chrome-trace export."""

    MAX_ROOTS = 64

    def __init__(self, enabled: bool | None = None):
        if enabled is None:
            enabled = os.environ.get(_OBS_ENV, "1") != "0"
        self.enabled = enabled
        self._tls = threading.local()
        # finished root spans, oldest first (bounded: a 99-query power
        # run must not retain every tree forever)
        self.last_roots: deque = deque(maxlen=self.MAX_ROOTS)
        # defer_exports=True parks finished roots on _pending instead
        # of writing them inline: the power loop's root spans end
        # INSIDE the timed bracket, and even a ~ms export skews the
        # span-vs-TimeLog agreement; the loop flushes after the bracket
        self.defer_exports = False
        self._pending: list = []
        # root spans begun but not yet ended: an abnormal exit (crash,
        # deadline kill that unwinds) salvages these as a truncated
        # trace instead of losing the in-flight query entirely
        self._open_roots: set = set()

    # ------------------------------------------------------------- stack

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if span in st:
            # tolerate mismatched exits: drop through to the span
            while st and st.pop() is not span:
                pass

    def current(self) -> "Span | None":
        st = self._stack()
        return st[-1] if st else None

    # --------------------------------------------------------------- API

    def span(self, name: str, **attrs):
        """Context-managed span, parented to the thread's current
        span."""
        if not self.enabled:
            return NOOP_SPAN
        s = Span(self, name, self.current(), attrs)
        if s.parent is None:
            self._open_roots.add(s)
        return s

    def begin(self, name: str, parent: "Span | None | object" = _CURRENT,
              t0: float | None = None, **attrs):
        """Explicitly-owned span (caller must ``end()`` it). ``parent``
        defaults to the thread's current span; pass ``None`` to force a
        root."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is _CURRENT:
            parent = self.current()
        elif isinstance(parent, _NoopSpan):
            parent = None
        s = Span(self, name, parent, attrs, t0=t0)
        if s.parent is None:
            self._open_roots.add(s)
        return s

    def attach(self, span) -> _Attach:
        """Make an owned span current for a ``with`` block (no end on
        exit). Accepts the no-op span and does nothing."""
        return _Attach(self, span if isinstance(span, Span) else None)

    # ------------------------------------------------------------ export

    def _finish_root(self, root: Span) -> None:
        self._open_roots.discard(root)
        self.last_roots.append(root)
        path = os.environ.get(TRACE_ENV)
        if not path:
            return
        if self.defer_exports:
            self._pending.append((root, path))
            return
        try:
            export_chrome(root, path)
        except OSError:  # tracing must never fail the query
            pass

    def flush_exports(self, close_roots: bool = False) -> None:
        """Write every parked root tree (defer_exports mode).
        Idempotent — the pending list drains on the first call, and a
        second call is a no-op. ``close_roots=True`` (the atexit path)
        first ends any still-open root span so a crashed or
        deadline-killed run leaves a readable, truncated trace instead
        of losing the in-flight tree."""
        if close_roots:
            self.defer_exports = False  # nothing re-parks at exit
            for root in list(self._open_roots):
                try:
                    root.set(truncated=True).end()
                except Exception:  # noqa: BLE001 - exit path
                    self._open_roots.discard(root)
        pending, self._pending = self._pending, []
        for root, path in pending:
            try:
                export_chrome(root, path)
            except OSError:
                pass
        if close_roots:
            with _EXPORT_LOCK:
                for f in _EXPORT_FILES.values():
                    try:
                        if not f.closed:
                            f.flush()
                    except OSError:
                        pass


# held-open export handles, one per trace path: the export runs inside
# the power loop's per-query timing bracket (root spans end there), and
# an open/close pair per query on a slow filesystem costs multiple ms —
# visible skew between span totals and the TimeLog CSV. Flushed per
# tree so readers always see complete trees; the OS closes at exit.
_EXPORT_FILES: dict = {}


def _append_events(events: list, path: str) -> None:
    """JSONL-append pre-built trace events through the held-open
    handle for ``path`` (shared by span trees and counter lanes)."""
    with _EXPORT_LOCK:
        f = _EXPORT_FILES.get(path)
        if f is None or f.closed:
            f = _EXPORT_FILES[path] = open(path, "a")
            if len(_EXPORT_FILES) > 8:  # bound leaked handles (tests)
                old = next(iter(_EXPORT_FILES))
                if old != path:
                    _EXPORT_FILES.pop(old).close()
        f.write("".join(json.dumps(ev) + "\n" for ev in events))
        f.flush()


def export_chrome(root: Span, path: str) -> None:
    """Append one JSONL line per span in ``root``'s tree to ``path``."""
    _append_events(root.to_events(), path)


def counter_event(name: str, values: dict, t: "float | None" = None,
                  pid: "int | None" = None) -> dict:
    """One Chrome-trace counter sample (``ph: "C"``): Perfetto renders
    each numeric key in ``values`` as a stacked counter lane next to
    the span tracks. ``t`` is a perf_counter timestamp (defaults to
    now) — exported on the same calibrated epoch as spans so lanes
    line up."""
    if t is None:
        t = time.perf_counter()
    return {"name": name, "cat": "counter", "ph": "C",
            "ts": (t + _EPOCH_OFFSET) * 1e6,
            "pid": export_pid() if pid is None else int(pid),
            "tid": 0,
            "args": {str(k): float(v) for k, v in values.items()}}


def export_counters(events: list, path: str) -> None:
    """Append counter events (``counter_event``) to a trace file —
    the device-memory telemetry lane rides the same JSONL stream as
    the spans."""
    if events:
        _append_events(events, path)


# timing keys the per-phase spans map onto (the legacy last_timings
# vocabulary — TimeLog/engineTimings consumers parse these names)
PHASE_TIMING_KEYS = {
    "device.compile": "compile_ms",
    "device.run": "execute_ms",
    "device.materialize": "materialize_ms",
}


def timings_from_span(root) -> dict:
    """last_timings-shaped dict from a query span tree: the executor
    attaches the authoritative dict as the root's ``timings`` attr
    (retry folding, staged-bill merge and roofline derivation live in
    the executor); absent that, phase child durations are summed under
    the legacy key names."""
    if not isinstance(root, Span):
        return {}
    t = root.attrs.get("timings")
    if isinstance(t, dict):
        return dict(t)
    out: dict = {}
    for s in root.walk():
        key = PHASE_TIMING_KEYS.get(s.name)
        if key:
            out[key] = out.get(key, 0.0) + s.dur_ms
    return out


_TRACER = Tracer()

# exit-time flush for the GLOBAL tracer only (per-instance registration
# would pin every test-constructed tracer and its span trees forever):
# a crashed/deadline-killed run keeps whatever the buffer held, and any
# still-open root exports as a truncated tree (idempotent — a clean run
# flushes nothing twice)
atexit.register(_TRACER.flush_exports, close_roots=True)


def get_tracer() -> Tracer:
    return _TRACER


def set_enabled(enabled: bool) -> None:
    """Test/CLI hook: flip the global tracer without rebuilding it."""
    _TRACER.enabled = enabled
