"""Live device-memory telemetry: HBM occupancy over time, not one HWM.

memwatch answers "how high did it get"; it cannot answer "when, for
how long, and was it climbing" — the questions an OOM post-mortem or
a prefetch-depth decision actually asks. This module is the
time-series twin: a background daemon thread (the
``obs/snapshot.MetricsSnapshotter`` lifecycle pattern) samples summed
per-device ``memory_stats()["bytes_in_use"]`` every
``obs.telemetry.interval_ms`` into a bounded ring of
``(perf_counter_t, bytes)`` samples, and three readouts drain it:

- ``query_block()`` — the per-query BenchReport ``telemetry`` block:
  sample count, interval, and an HBM min/max/mean plus a decimated
  ``series`` of ``[t_offset_ms, bytes]`` points (at most
  SERIES_MAX_POINTS — a summary, not a firehose);
- ``snapshot_block()`` — the live-metrics-snapshot lane
  (obs/snapshot.py) so a watcher sees occupancy mid-run;
- ``drain_counter_events()`` — timestamped samples for Chrome-trace
  counter lanes (obs/trace.export_counters) so Perfetto renders a
  device-memory track under the span tree.

Backends without allocator stats (CPU, virtual mesh) are a graceful
no-op: the default reader is memwatch's guarded device probe — it
never initializes a backend (the dead-tunnel rule) and returns None,
so the ring stays empty, every block is None, and summaries/snapshots
keep their pre-telemetry shape byte-identically.

Config: ``obs.telemetry.enabled`` (default on — the sampler is idle
on no-stats backends anyway) and ``obs.telemetry.interval_ms``
(default 250). Env ``NDS_TPU_TELEMETRY`` overrides: ``off``/``0``
disables, a number becomes the interval in ms. All mutation is under
one locksan-registered lock; start/stop are idempotent.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from nds_tpu.analysis import locksan

_LOCK = locksan.lock("obs.telemetry._LOCK")

TELEMETRY_ENV = "NDS_TPU_TELEMETRY"
DEFAULT_INTERVAL_MS = 250
DEFAULT_CAPACITY = 512
SERIES_MAX_POINTS = 64


def _decimate(samples: list) -> list:
    """At most SERIES_MAX_POINTS evenly-strided samples, endpoints
    kept — the block is a shape summary, not a raw dump."""
    n = len(samples)
    if n <= SERIES_MAX_POINTS:
        return list(samples)
    stride = (n - 1) / (SERIES_MAX_POINTS - 1)
    return [samples[min(n - 1, round(i * stride))]
            for i in range(SERIES_MAX_POINTS)]


class TelemetrySampler:
    """Bounded-ring background sampler of device bytes-in-use."""

    def __init__(self, interval_ms: float = DEFAULT_INTERVAL_MS,
                 capacity: int = DEFAULT_CAPACITY, read_fn=None):
        from nds_tpu.obs import memwatch
        self.interval_ms = max(1.0, float(interval_ms))
        self.capacity = max(2, int(capacity))
        self._read_fn = read_fn or memwatch._device_bytes_in_use
        self._ring: deque = deque(maxlen=self.capacity)
        self._query_t0 = time.perf_counter()
        self._drained_t = float("-inf")
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    # --------------------------------------------------------- lifecycle

    def start(self) -> "TelemetrySampler":
        """Idempotent: a running sampler keeps running."""
        with _LOCK:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="nds-tpu-telemetry",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent; takes one final sample so short windows still
        carry at least one point on stats-capable backends."""
        with _LOCK:
            thread, self._thread = self._thread, None
        if thread is None:
            return
        self._stop.set()
        thread.join(timeout=5.0)
        self.sample()

    def running(self) -> bool:
        with _LOCK:
            return self._thread is not None

    def _loop(self) -> None:
        # sample at t=0, then every interval until stopped
        self.sample()
        while not self._stop.wait(self.interval_ms / 1000.0):
            self.sample()

    # ---------------------------------------------------------- sampling

    def sample(self) -> None:
        """One reading into the ring; silently nothing on backends
        without stats (telemetry must never fail or slow a query)."""
        try:
            v = self._read_fn()
        except Exception:  # noqa: BLE001 - gauge, not a query step
            v = None
        if v is None:
            return
        t = time.perf_counter()
        with _LOCK:
            self._ring.append((t, int(v)))

    # ---------------------------------------------------------- readouts

    def reset_query(self) -> None:
        """Open a fresh per-query window (the power loop's per-query
        reset point, next to memwatch.reset_query)."""
        with _LOCK:
            self._query_t0 = time.perf_counter()

    def _window(self) -> list:
        with _LOCK:
            t0 = self._query_t0
            return [s for s in self._ring if s[0] >= t0]

    def query_block(self) -> "dict | None":
        """BenchReport ``telemetry`` block for the current query
        window, or None when no samples landed (no-stats backends,
        sub-interval queries)."""
        window = self._window()
        if not window:
            return None
        t0 = window[0][0]
        vals = [b for _t, b in window]
        return {
            "samples": len(window),
            "interval_ms": self.interval_ms,
            "hbm": {
                "min_bytes": min(vals),
                "max_bytes": max(vals),
                "mean_bytes": int(sum(vals) / len(vals)),
                "series": [[round((t - t0) * 1000.0, 3), b]
                           for t, b in _decimate(window)],
            },
        }

    def snapshot_block(self) -> "dict | None":
        """Compact lane for the live metrics snapshot: ring-wide count
        plus the latest reading, or None when the ring is empty."""
        with _LOCK:
            if not self._ring:
                return None
            t, b = self._ring[-1]
            return {"samples": len(self._ring),
                    "interval_ms": self.interval_ms,
                    "last_bytes": b,
                    "age_s": round(time.perf_counter() - t, 3)}

    def drain_counter_events(self) -> list:
        """Samples newer than the previous drain, as ``(t, bytes)``
        with perf_counter timestamps (trace.py's clock) — the feed for
        Chrome counter lanes. The drain mark is independent of ring
        retention: each sample exports at most once."""
        with _LOCK:
            out = [s for s in self._ring if s[0] > self._drained_t]
            if out:
                self._drained_t = out[-1][0]
            return out


# ------------------------------------------------------ module lifecycle

_ACTIVE: "TelemetrySampler | None" = None


def configured_interval_ms(config=None) -> "float | None":
    """The effective sampling interval, or None when telemetry is
    disabled. Env NDS_TPU_TELEMETRY wins over ``obs.telemetry.*``
    config keys."""
    env = os.environ.get(TELEMETRY_ENV)
    if env is not None:
        env = env.strip().lower()
        if env in ("off", "0", "false", "no"):
            return None
        try:
            return max(1.0, float(env))
        except ValueError:
            pass  # unparseable env falls through to config
    if config is not None:
        try:
            if not config.get_bool("obs.telemetry.enabled", True):
                return None
            return float(config.get_int("obs.telemetry.interval_ms",
                                        DEFAULT_INTERVAL_MS))
        except Exception:  # noqa: BLE001 - config typo: use defaults
            return float(DEFAULT_INTERVAL_MS)
    return float(DEFAULT_INTERVAL_MS)


def start_from_config(config=None) -> "TelemetrySampler | None":
    """Start (or return the already-running) module sampler per
    config/env; None when disabled. The power loop's entry point."""
    global _ACTIVE
    interval = configured_interval_ms(config)
    if interval is None:
        return None
    with _LOCK:
        sampler = _ACTIVE
    if sampler is not None and sampler.running():
        return sampler
    sampler = TelemetrySampler(interval_ms=interval)
    with _LOCK:
        _ACTIVE = sampler
    return sampler.start()


def active() -> "TelemetrySampler | None":
    with _LOCK:
        return _ACTIVE


def stop() -> None:
    sampler = active()
    if sampler is not None:
        sampler.stop()


def reset_query() -> None:
    sampler = active()
    if sampler is not None:
        sampler.reset_query()


def query_block() -> "dict | None":
    sampler = active()
    return sampler.query_block() if sampler is not None else None


def snapshot_block() -> "dict | None":
    sampler = active()
    return sampler.snapshot_block() if sampler is not None else None


def drain_counter_events() -> list:
    sampler = active()
    return (sampler.drain_counter_events()
            if sampler is not None else [])
