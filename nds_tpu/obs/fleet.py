"""Fleet observability: per-rank trace shards, clock alignment, and
the always-on flight recorder.

Everything the obs layer built so far — tracer, metrics, memwatch,
snapshots — is rank-local and host-side: a multi-host run produces one
trace file per process with UNALIGNED clocks (each rank's Chrome ``ts``
is its own ``time.time()`` calibration), no way to tell which rank
stalled a collective, and no post-mortem at all when a supervisor
kills a wedged process before the trace buffer flushed. This module is
the fleet-side contract:

- **Per-rank trace shards** (``init_fleet``): on a multi-process world
  every rank re-points ``NDS_TPU_TRACE`` at its own
  ``<base>-r<rank>.jsonl`` shard (shared storage, no write collisions),
  pins the Chrome-trace export pid to the RANK (deterministic lanes —
  obs/trace.set_export_pid), and writes a ``fleet-r<rank>.json``
  sidecar stamped with ``(rank, world, host, pid, boot_offset_s)`` so
  ``ndsreport analyze`` can merge every shard into one clock-aligned
  fleet timeline (obs/analyze.py consumes the sidecars).

- **Clock handshake** (``clock_handshake``): an allgather barrier over
  the same DCN channel as the placement-consensus votes
  (parallel/multihost.gather_floats) — no rank's clock read happens
  before every rank entered the collective, so the readings are taken
  at (approximately) one fleet-wide instant and the per-rank offsets
  ``t_r - t_0`` correct exactly the clock basis the exported events
  are stamped with (obs/trace.epoch_offset). A failed gather degrades
  to unaligned shards (``aligned: false`` in the sidecar), never a
  hang.

- **Flight recorder** (``FlightRecorder``): a bounded in-memory ring
  of the last N completed span trees + per-query metric deltas,
  dumped ATOMICALLY to ``flight-r<rank>.json`` on watchdog stall (via
  the stall-hook registry, so the stall report points at the dump),
  on a query's final-attempt failure / a ``CorruptArtifact`` load
  failure, and on SIGTERM (the supervisor-kill path) — a dead stream
  in a multi-hour run leaves a post-mortem even when its full trace
  file never flushed. ``NDS_TPU_FLIGHT=N`` resizes the ring (0
  disables); dumps count on ``flight_dumps_total``.
"""

from __future__ import annotations

import os
import re
import signal
import socket
import threading
import time
from collections import deque

from nds_tpu.analysis import locksan
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.obs import trace as obs_trace

SIDECAR_PREFIX = "fleet-r"
FLIGHT_PREFIX = "flight-r"
FLIGHT_ENV = "NDS_TPU_FLIGHT"
DEFAULT_RING = 16

# stream names a supervisor assigns end in their index (query_3,
# query_3#r1): the deterministic export pid for subprocess throughput
# traces, replacing colliding / run-arbitrary OS pids
_STREAM_IDX_RE = re.compile(r"_(\d+)(?:#r\d+)?$")


def rank_info(distributed: bool = False) -> dict:
    """``{rank, world, host, pid}``. The world is probed from the
    jax.distributed COORDINATION state (``global_state.process_id`` /
    ``num_processes``) — never from a backend accessor, which would
    force platform discovery and can block on a dead remote-chip
    tunnel (the report.capture_env contract). A process that never
    called ``jax.distributed.initialize`` is a rank-0 world-of-1;
    ``distributed`` only widens the probe to jax's own accessors as a
    fallback (the distributed backend has already initialized)."""
    rank, world = 0, 1
    try:
        from jax._src import distributed as jdist
        st = jdist.global_state
        if getattr(st, "client", None) is not None \
                and (st.num_processes or 0) > 1:
            rank, world = st.process_id, st.num_processes
        elif distributed:
            import jax
            rank, world = jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 - no jax / private-API drift
        pass
    return {"rank": int(rank), "world": int(world),
            "host": socket.gethostname(), "pid": os.getpid()}


_handshake_seq = 0

# the operator's ORIGINAL trace base, memoized before the first shard
# re-point: init_fleet mutates NDS_TPU_TRACE in place (children and
# later exports must see the shard), so a second run in the same
# process would otherwise shard the already-sharded name
# (trace-r0-r0.jsonl)
_trace_base: "str | None" = None


def clock_handshake() -> "list[float] | None":
    """Per-rank clock offsets (seconds, ``offset[r] = t_r - t_0``)
    measured around a coordination-service barrier: the barrier
    releases every rank at (approximately) one fleet-wide instant,
    the clock reads happen in the narrow window right after it, and a
    KV-store allgather ships them (parallel/multihost.gather_floats —
    the same coordination channel the consensus layer rides). The
    reading is ``perf_counter + epoch_offset`` — the exact basis
    exported Chrome ``ts`` values use, so subtracting ``offset[r]``
    from rank r's events puts every shard on rank 0's timeline. None
    on barrier/gather failure (caller degrades to unaligned)."""
    global _handshake_seq
    from nds_tpu.parallel import multihost
    _handshake_seq += 1
    if not multihost.barrier(f"nds_tpu/clock/{_handshake_seq}"):
        return None
    reading = time.perf_counter() + obs_trace.epoch_offset()
    votes = multihost.gather_floats(reading)
    if votes is None:
        return None
    return [v - votes[0] for v in votes]


def shard_path(base: str, rank: int) -> str:
    """``/runs/trace.jsonl`` -> ``/runs/trace-r3.jsonl``."""
    root, ext = os.path.splitext(base)
    return f"{root}-r{rank}{ext or '.jsonl'}"


def init_fleet(run_dir: str | None,
               distributed: bool = False) -> "dict | None":
    """Session-start fleet wiring (called by the power loop after the
    session exists, so the SPMD world is initialized and every rank
    enters the handshake together).

    Single-process worlds only pin the deterministic export pid (the
    stream index when a supervisor named this process) and return
    None. Multi-rank worlds additionally: run the clock handshake,
    re-point ``NDS_TPU_TRACE`` at this rank's shard, pin
    ``export pid = rank``, and write the ``fleet-r<rank>.json``
    sidecar into ``run_dir``. Returns the sidecar dict."""
    info = rank_info(distributed)
    if info["world"] <= 1:
        stream = os.environ.get("NDS_TPU_STREAM")
        m = _STREAM_IDX_RE.search(stream or "")
        if m:
            obs_trace.set_export_pid(int(m.group(1)))
        return None
    rank = info["rank"]
    obs_trace.set_export_pid(rank)
    offsets = clock_handshake()
    doc = dict(info)
    doc["boot_offset_s"] = (round(offsets[rank], 6)
                            if offsets is not None else 0.0)
    doc["aligned"] = offsets is not None
    if offsets is not None:
        doc["offsets_s"] = [round(o, 6) for o in offsets]
    global _trace_base
    base = (_trace_base if _trace_base is not None
            else os.environ.get(obs_trace.TRACE_ENV))
    if base:
        _trace_base = base
        shard = shard_path(base, rank)
        os.environ[obs_trace.TRACE_ENV] = shard
        doc["trace_shard"] = os.path.basename(shard)
    doc["ts"] = time.time()
    if run_dir:
        from nds_tpu.io.integrity import write_json_atomic
        os.makedirs(run_dir, exist_ok=True)
        write_json_atomic(
            os.path.join(run_dir, f"{SIDECAR_PREFIX}{rank}.json"), doc)
    return doc


def load_fleet(run_dir: str) -> "list[dict]":
    """Every rank sidecar under ``run_dir`` (non-recursive — sidecars
    land next to the summaries), rank-sorted. [] when the run was not
    a fleet (single-process dirs analyze exactly as before)."""
    import json
    out = []
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        return []
    for name in names:
        if not (name.startswith(SIDECAR_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(run_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and "rank" in doc:
            out.append(doc)
    out.sort(key=lambda d: d.get("rank", 0))
    return out


# ------------------------------------------------------ flight recorder

class FlightRecorder:
    """Bounded ring of the last N completed queries' span trees +
    metric deltas, dumpable as one atomic post-mortem JSON."""

    def __init__(self, run_dir: str, rank: int = 0,
                 maxlen: int | None = None):
        if maxlen is None:
            try:
                maxlen = int(os.environ.get(FLIGHT_ENV, DEFAULT_RING))
            except ValueError:
                maxlen = DEFAULT_RING
        self.run_dir = run_dir or "."
        self.rank = int(rank)
        self.enabled = maxlen > 0
        self.ring: deque = deque(maxlen=max(maxlen, 1))
        self.dumps = 0
        self.reasons: list[str] = []
        self._lock = locksan.lock("obs.FlightRecorder._lock")

    @property
    def path(self) -> str:
        return os.path.join(self.run_dir,
                            f"{FLIGHT_PREFIX}{self.rank}.json")

    def record(self, query: str, status: str, root_span=None,
               wall_ms: float | None = None,
               metrics_delta: dict | None = None) -> None:
        """One completed (or finally-failed) query into the ring. The
        span tree serializes NOW — a later dump must not chase live
        Span objects from the watchdog thread."""
        if not self.enabled:
            return
        entry: dict = {"query": query, "status": status,
                       "ts": time.time()}
        if wall_ms is not None:
            entry["wall_ms"] = round(float(wall_ms), 3)
        if root_span is not None and isinstance(root_span,
                                                obs_trace.Span):
            try:
                entry["spans"] = root_span.to_dict()
            except Exception:  # noqa: BLE001 - recorder never fails a query
                pass
        if metrics_delta:
            entry["metrics"] = metrics_delta
        with self._lock:
            self.ring.append(entry)

    def _gather(self, reason: str) -> dict:
        """Lock-taking part of a dump (ring + metrics + heartbeats)."""
        from nds_tpu.resilience import watchdog
        with self._lock:
            entries = list(self.ring)
            self.dumps += 1
            self.reasons.append(reason)
            reasons, dumps = list(self.reasons), self.dumps
        return {
            "rank": self.rank,
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "reason": reason,
            "reasons": reasons,
            "dumps": dumps,
            "ts": time.time(),
            "entries": entries,
            "metrics": obs_metrics.snapshot(),
            "heartbeats": watchdog.snapshot_heartbeats(),
        }

    # ndsraces: waive[NDSR203] -- bounded boundary: lock-taking gather runs on a worker thread joined with timeout_s on the signal path
    def dump(self, reason: str,
             timeout_s: "float | None" = None) -> "str | None":
        """Atomic ``flight-r<rank>.json`` write (latest dump wins; the
        ``reasons`` list keeps the trigger history). Never raises —
        a post-mortem writer that crashes the process it is documenting
        would be worse than no dump.

        ``timeout_s`` is the SIGNAL-HANDLER mode: the handler runs on
        the main thread, which may have been interrupted INSIDE one of
        the locks this dump needs (the ring lock, the watchdog/metrics
        registry locks) — acquiring them inline would self-deadlock
        and absorb the SIGTERM forever. The lock-taking gather then
        runs in a bounded worker thread; on timeout a partial header
        doc is written instead of blocking the handler."""
        if not self.enabled:
            return None
        if timeout_s is None:
            doc = self._gather(reason)
        else:
            box: dict = {}

            def _worker():
                box["doc"] = self._gather(reason)

            t = threading.Thread(target=_worker,
                                 name="nds-tpu-flight-dump",
                                 daemon=True)
            t.start()
            t.join(timeout=timeout_s)
            doc = box.get("doc") or {
                "rank": self.rank, "host": socket.gethostname(),
                "pid": os.getpid(), "reason": reason,
                # ndsraces: waive[NDSR201] -- signal-path fallback: taking the ring lock here is the self-deadlock this branch avoids
                "reasons": [reason], "dumps": self.dumps + 1,
                "ts": time.time(), "entries": [], "metrics": {},
                "partial": True,
            }
        try:
            from nds_tpu.io.integrity import write_json_atomic
            # write_json_atomic's tmp names are thread-unique, so the
            # watchdog thread (a stall dump) and the main thread (a
            # SIGTERM dump — the exact stall-then-supervisor-kill
            # sequence) can dump the same recorder concurrently
            write_json_atomic(self.path, doc)
        except Exception as exc:  # noqa: BLE001 - post-mortem best effort
            print(f"[obs] flight-recorder dump failed: "
                  f"{type(exc).__name__}: {exc}")
            return None
        if timeout_s is None:
            # not on the signal path: the registry lock may be held by
            # the very frame the handler interrupted
            obs_metrics.counter("flight_dumps_total").inc()
        return self.path


_RECORDER: "FlightRecorder | None" = None


def _flight_stall_hook(run_dir: str, entry: dict) -> "dict | None":
    rec = _RECORDER
    if rec is None:
        return None
    path = rec.dump(f"stall:{entry.get('query') or entry.get('phase')}")
    return {"flight": path} if path else None


def arm_flight_recorder(run_dir: str,
                        rank: int = 0) -> "FlightRecorder | None":
    """Install the process-wide recorder for this run (replacing any
    previous run's), register its watchdog stall hook, and install the
    SIGTERM dump. Returns None when ``NDS_TPU_FLIGHT=0``."""
    global _RECORDER
    from nds_tpu.resilience import watchdog
    rec = FlightRecorder(run_dir, rank=rank)
    if not rec.enabled:
        _RECORDER = None
        watchdog.unregister_stall_hook(_flight_stall_hook)
        return None
    _RECORDER = rec
    watchdog.register_stall_hook(_flight_stall_hook)
    _install_sigterm()
    return rec


def flight_recorder() -> "FlightRecorder | None":
    return _RECORDER


def disarm_flight_recorder() -> None:
    """End-of-run teardown: later runs in this process re-arm with
    their own dir (the SIGTERM handler stays installed — it no-ops
    with no recorder armed)."""
    global _RECORDER
    from nds_tpu.resilience import watchdog
    _RECORDER = None
    watchdog.unregister_stall_hook(_flight_stall_hook)


def signal_flush(reason: str = "sigterm",
                 timeout_s: float = 2.0) -> None:
    """The SIGNAL-PATH post-mortem flush, callable from any handler
    (the SIGTERM chain below AND the drain manager's handlers,
    resilience/drain.py): dump the armed recorder and flush any parked
    trace roots, both BOUNDED — the interrupted frame may hold the
    very locks the dump and the export need (see FlightRecorder.dump),
    so neither step may block the handler forever."""
    rec = _RECORDER
    if rec is not None:
        rec.dump(reason, timeout_s=timeout_s)

    def _flush():
        try:
            obs_trace.get_tracer().flush_exports(close_roots=True)
        except Exception:  # noqa: BLE001 - dying anyway
            pass

    ft = threading.Thread(target=_flush, daemon=True)
    ft.start()
    ft.join(timeout=1.0)


_sigterm_installed = False


def _install_sigterm() -> None:
    """Chainable SIGTERM handler (installed once per process, main
    thread only): dump the armed recorder + flush any parked trace
    roots, then hand the signal to whatever handler was there before —
    the supervisor's kill escalation still sees a SIGTERM death, with
    a flight dump on disk next to the stall report. When a drain
    manager is installed on top (resilience/drain.py — the power loop
    installs it AFTER this), ITS handler runs instead and performs the
    same flush via signal_flush before draining resumably."""
    global _sigterm_installed
    if _sigterm_installed:
        return
    if threading.current_thread() is not threading.main_thread():
        return
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            signal_flush("sigterm")
            if callable(prev):
                prev(signum, frame)
            elif prev != signal.SIG_IGN:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
        _sigterm_installed = True
    except (ValueError, OSError):
        # not the main thread / exotic platform: the stall + failure
        # dump paths still cover the ring
        pass
