"""On-demand XLA profiler capture behind a trigger policy.

The span tracer sees the engine's phases; it cannot see INSIDE a
compiled program — which fusion dominated, whether a collective sat
waiting, what the MXU actually did. ``jax.profiler`` can, but leaving
it on for a multi-hour run is a disk- and overhead-disaster. This
module is the Execution-Templates-shaped compromise (PAPERS.md):
validate cheaply always (spans + metrics), pay for deep capture only
when a trigger says a query deserves it.

Triggers (``engine.profile.{mode,slow_query_ms,dir}`` config keys, or
``NDS_TPU_PROFILE=<mode>@<dir>`` for subprocess fleets):

- ``mode`` names queries explicitly (``query21`` or
  ``query21,query72``) — those queries capture on every run;
- ``mode=all`` captures every query (short diagnostic streams);
- ``mode=slow`` captures any query whose PREVIOUS run in this process
  exceeded ``slow_query_ms`` (the first slow run arms the trigger, the
  next run pays the capture — a steady-state profile, not the
  compile-tainted first one);
- ``mode=stall`` (the env default) arms only the watchdog hook below.

Whenever a profiler is configured, a watchdog stall additionally
REQUESTS an on-demand capture (via the resilience/watchdog stall-hook
registry): the hook reserves the capture path — pure bookkeeping, so
the stall report can point at it (``profile`` key) — and the MAIN
thread takes the capture at its next dispatch safe-point, bracketing
the first post-stall query into exactly that path. Deferred on
purpose: ``start_trace`` from a non-main thread wedges against an
active main thread on this jaxlib (and a wedged hook would disarm the
watchdog's own kill action), so a transient stall leaves device-level
evidence and a hard hang still leaves the flight dump + stacks.

Every capture lands under ``dir`` as its own subdirectory, is recorded
in the query's BenchReport as the ``profile`` block
``{path, trigger, bytes}`` (validated by ``tools/check_trace_schema.py
--summary``), and counts on ``profile_captures_total``. All
``jax.profiler`` entry points live HERE — ndslint NDS113 flags
``start_trace`` calls anywhere else — and every capture failure
degrades to a warning, never a query failure.
"""

from __future__ import annotations

import contextlib
import os

from nds_tpu.analysis import locksan
from nds_tpu.obs import metrics as obs_metrics

PROFILE_ENV = "NDS_TPU_PROFILE"

# trigger vocabulary the BenchReport profile block carries
TRIGGERS = ("query", "slow", "stall", "stream")


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                continue
    return total


class ProfilePolicy:
    """Parsed trigger configuration (pure — unit-testable without
    jax)."""

    def __init__(self, out_dir: str, mode: str = "stall",
                 slow_query_ms: float = 0.0):
        self.out_dir = out_dir
        self.mode = (mode or "stall").strip()
        self.slow_query_ms = float(slow_query_ms or 0.0)
        self.queries = ()
        if self.mode not in ("all", "slow", "stall"):
            self.queries = tuple(
                q.strip() for q in self.mode.split(",") if q.strip())

    @classmethod
    def from_config(cls, config) -> "ProfilePolicy | None":
        """``engine.profile.dir`` activates; mode/slow_query_ms shape
        the trigger. Falls back to ``NDS_TPU_PROFILE=<mode>@<dir>``
        (mode optional — bare ``dir`` arms stall-only capture;
        ``slow=MS`` spells the slow trigger inline)."""
        d = config.get("engine.profile.dir") if config else None
        if d:
            return cls(str(d),
                       str(config.get("engine.profile.mode", "stall")),
                       float(config.get("engine.profile.slow_query_ms",
                                        0) or 0))
        spec = os.environ.get(PROFILE_ENV)
        if not spec:
            return None
        mode, sep, out_dir = spec.rpartition("@")
        if not sep:
            return cls(spec)
        slow_ms = 0.0
        if mode.startswith("slow="):
            slow_ms, mode = float(mode[len("slow="):]), "slow"
        return cls(out_dir, mode, slow_ms)

    def trigger_for(self, qname: str,
                    prev_ms: "float | None") -> "str | None":
        """Pre-query decision: capture this run? (``stall`` mode never
        pre-triggers — it only arms the watchdog hook.)"""
        if self.mode == "all" or qname in self.queries:
            return "query"
        if (self.mode == "slow" and self.slow_query_ms > 0
                and prev_ms is not None
                and prev_ms > self.slow_query_ms):
            return "slow"
        return None


class Profiler:
    """The engine's ONE ``jax.profiler`` owner: programmatic
    start/stop captures with per-query history for the slow trigger."""

    def __init__(self, policy: ProfilePolicy):
        self.policy = policy
        # query name -> last observed wall-clock ms (the slow trigger's
        # "previous run" memory; process-local by design — a serving
        # process watches its own latency)
        self.history: dict[str, float] = {}
        self._lock = locksan.lock("obs.Profiler._lock")
        self._active = False
        self._warned = False
        self._seq = 0
        # capture path a stall hook reserved for the main thread to
        # fill at its next dispatch safe-point (take_pending)
        self._pending: "str | None" = None

    # ------------------------------------------------------- decisions

    def trigger_for(self, qname: str) -> "str | None":
        return self.policy.trigger_for(qname, self.history.get(qname))

    def observe(self, qname: str, elapsed_ms: float) -> None:
        self.history[qname] = float(elapsed_ms)

    # -------------------------------------------------------- captures

    def _capture_dir(self, label: str) -> str:
        with self._lock:
            self._seq += 1
            seq = self._seq
        safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                       for c in label)
        # pid-suffixed: ranks/streams of one fleet share out_dir, and
        # the profiler names its files by HOSTNAME — two processes on
        # one host writing the same capture dir would collide
        return os.path.join(self.policy.out_dir,
                            f"{safe}-p{os.getpid()}-{seq}")

    def _start(self, path: str) -> bool:
        """Begin a capture (False when one is already running — jax
        allows a single active trace per process)."""
        with self._lock:
            if self._active:
                return False
            self._active = True
        try:
            import jax
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            return True
        except Exception as exc:  # noqa: BLE001 - never fail the query
            with self._lock:
                self._active = False
            self._warn(exc)
            return False

    def _stop(self) -> None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 - never fail the query
            self._warn(exc)
        finally:
            with self._lock:
                self._active = False

    def _warn(self, exc: BaseException) -> None:
        obs_metrics.counter("profile_errors_total").inc()
        if not self._warned:
            self._warned = True
            print(f"[obs] XLA profiler capture failed: "
                  f"{type(exc).__name__}: {exc}")

    @contextlib.contextmanager
    def capture(self, qname: str, trigger: str,
                path: "str | None" = None):
        """Context manager bracketing one query's capture; yields the
        ``profile`` block dict (empty when the capture could not run —
        callers attach it only when a ``path`` landed). ``path``
        overrides the capture directory — the stall-drain path, where
        the stall report already published where the capture will
        be."""
        info: dict = {}
        path = path or self._capture_dir(qname)
        started = self._start(path)
        try:
            yield info
        finally:
            if started:
                self._stop()
                info.update({"path": path, "trigger": trigger,
                             "bytes": _dir_bytes(path)})
                obs_metrics.counter("profile_captures_total").inc()

    def request_stall_capture(self, label: str) -> str:
        """Reserve (and return) the capture path for a stall — called
        from the WATCHDOG thread, so it must not touch the profiler or
        jax at all: ``start_trace`` from a non-main thread wedges
        against an active main thread on this jaxlib, and a wedged
        hook would disarm the watchdog's kill action. The main thread
        drains the reservation at its next dispatch safe-point
        (``take_pending``) and captures the first post-stall query
        into exactly this path; repeat stalls before the drain share
        the one reservation."""
        # path computed BEFORE taking the lock: _capture_dir takes the
        # same (non-reentrant) lock for its sequence number
        path = self._capture_dir(f"stall-{label}")
        with self._lock:
            if self._pending is None:
                self._pending = path
            return self._pending

    def take_pending(self) -> "str | None":
        """Claim the reserved stall-capture path (main thread, once)."""
        with self._lock:
            path, self._pending = self._pending, None
            return path

    def requeue_pending(self, path: str) -> None:
        """Put a claimed-but-unfilled reservation back (the capture
        failed to start): the stall report's pointer keeps its chance
        of being filled by a later query."""
        with self._lock:
            if self._pending is None:
                self._pending = path


_PROFILER: "Profiler | None" = None


def _stall_hook(run_dir: str, entry: dict) -> "dict | None":
    prof = _PROFILER
    if prof is None:
        return None
    path = prof.request_stall_capture(
        str(entry.get("query") or entry.get("phase") or "unknown"))
    if not path:
        return None
    # forward declaration, stated as one: the capture lands at this
    # path when the run reaches its next dispatch — a hard hang or a
    # kill-action exit leaves the pointer unfilled by design
    return {"profile": path, "profile_pending": True}


def configure(config) -> "Profiler | None":
    """Build + install the process profiler for this run (None when no
    policy is configured — the common case costs one dict lookup).
    Registers the watchdog stall hook while armed. A malformed spec
    (``NDS_TPU_PROFILE=slow=fast@/d``, a non-numeric
    ``engine.profile.slow_query_ms``) degrades to a warned no-profiler
    run — an observability typo must never fail the benchmark."""
    global _PROFILER
    from nds_tpu.resilience import watchdog
    try:
        policy = ProfilePolicy.from_config(config)
    except Exception as exc:  # noqa: BLE001 - degrade, never fail a run
        obs_metrics.counter("profile_errors_total").inc()
        print(f"[obs] bad profile config ignored: "
              f"{type(exc).__name__}: {exc}")
        policy = None
    if policy is None:
        _PROFILER = None
        watchdog.unregister_stall_hook(_stall_hook)
        return None
    _PROFILER = Profiler(policy)
    watchdog.register_stall_hook(_stall_hook)
    return _PROFILER


def profiler() -> "Profiler | None":
    return _PROFILER


def teardown() -> None:
    """End-of-run teardown: drop the trigger profiler, its stall hook,
    and any stream trace an exception carried past the power loop."""
    global _PROFILER
    from nds_tpu.resilience import watchdog
    _PROFILER = None
    watchdog.unregister_stall_hook(_stall_hook)
    end_stream_trace()


# whole-stream trace state: begin/end split (instead of only a context
# manager) so the power loop's OUTER finally can close a trace an
# exception carried past the loop — a leaked active trace wedges every
# later capture in the process (single-active-trace invariant)
_stream_active = False


def begin_stream_trace(profile_dir: "str | None") -> bool:
    """Open the whole-stream capture (the power drivers'
    ``--profile_dir``): one trace spanning every query, each
    annotated. The jax.profiler start/stop pair lives here so NDS113
    holds stack-wide. Returns whether a trace is now active."""
    global _stream_active
    if not profile_dir or _stream_active:
        return bool(_stream_active)
    import jax
    os.makedirs(profile_dir, exist_ok=True)
    jax.profiler.start_trace(profile_dir)
    _stream_active = True
    return True


def end_stream_trace() -> None:
    """Close the whole-stream capture (idempotent — the power loop
    calls it on the normal path AND from its outer finally)."""
    global _stream_active
    if not _stream_active:
        return
    _stream_active = False
    import jax
    try:
        jax.profiler.stop_trace()
    except Exception as exc:  # noqa: BLE001 - teardown best effort
        print(f"[obs] stream trace stop failed: "
              f"{type(exc).__name__}: {exc}")


@contextlib.contextmanager
def stream_trace(profile_dir: "str | None"):
    """Context-managed form of begin/end_stream_trace."""
    try:
        yield begin_stream_trace(profile_dir)
    finally:
        end_stream_trace()


def annotate(qname: str):
    """Named TraceAnnotation for one query inside a stream capture
    (the jax-profiler analog of the reference's setJobGroup)."""
    import jax
    return jax.profiler.TraceAnnotation(qname)
