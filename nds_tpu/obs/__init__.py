"""Unified observability layer: span tracing + metrics.

``nds_tpu.obs.trace`` — nestable wall-clock spans with Chrome-trace
JSONL export (``NDS_TPU_TRACE=path``); ``nds_tpu.obs.metrics`` — the
global counter/gauge/histogram registry.  ``query_timings`` is the
span-fed replacement for scraping ``executor.last_timings`` by hand.
"""

from __future__ import annotations

from nds_tpu.obs import metrics, trace
from nds_tpu.obs.trace import get_tracer

__all__ = ["metrics", "trace", "get_tracer", "query_timings"]


def query_timings(executor) -> dict:
    """Timing breakdown of the executor's last query, fed by its query
    span (``executor.last_query_span``).  Falls back to the legacy
    ``last_timings`` dict for executors that predate spans (or when
    tracing is disabled), so callers see the same key vocabulary either
    way: compile_ms / execute_ms / materialize_ms / bytes_scanned /
    scan_gbps / roofline_frac / roofline_peak_gbps / staged_programs.
    Executors without timings (the CPU oracle) yield {}."""
    root = getattr(executor, "last_query_span", None)
    if root:
        t = trace.timings_from_span(root)
        if t:
            return t
    return dict(getattr(executor, "last_timings", None) or {})
