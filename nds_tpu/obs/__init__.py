"""Unified observability layer: tracing, metrics, memory, analysis.

``nds_tpu.obs.trace`` — nestable wall-clock spans with Chrome-trace
JSONL export (``NDS_TPU_TRACE=path``); ``nds_tpu.obs.metrics`` — the
global counter/gauge/histogram registry; ``nds_tpu.obs.memwatch`` —
per-query device-memory high-water marks; ``nds_tpu.obs.snapshot`` —
the live metrics emitter (``NDS_TPU_METRICS_SNAP``);
``nds_tpu.obs.analyze`` — run-dir ingestion, time attribution, the
cross-run regression gate, and the HTML report behind
``tools/ndsreport.py``; ``nds_tpu.obs.fleet`` — per-rank trace
shards, the clock-alignment handshake, and the always-on flight
recorder; ``nds_tpu.obs.profile`` — on-demand XLA profiler capture
behind a trigger policy (``NDS_TPU_PROFILE``).  ``query_timings`` is
the span-fed replacement for scraping ``executor.last_timings`` by
hand.

``nds_tpu.obs.costs`` holds the compiler-truth cost ledger (XLA
``cost_analysis``/``memory_analysis`` per compiled program, billed per
dispatch into the BenchReport ``cost`` block) and
``nds_tpu.obs.telemetry`` the live device-memory sampler behind the
``telemetry`` block and the Chrome-trace counter lanes.

``analyze``/``snapshot``/``fleet``/``profile``/``costs``/``telemetry``
import lazily on attribute access — the hot engine path pays for spans
and counters only.
"""

from __future__ import annotations

from nds_tpu.obs import memwatch, metrics, trace
from nds_tpu.obs.trace import get_tracer

__all__ = ["analyze", "costs", "fleet", "memwatch", "metrics",
           "profile", "snapshot", "telemetry", "trace", "get_tracer",
           "query_timings"]


def __getattr__(name: str):
    if name in ("analyze", "snapshot", "fleet", "profile", "costs",
                "telemetry"):
        import importlib
        return importlib.import_module(f"nds_tpu.obs.{name}")
    raise AttributeError(name)


def query_timings(executor) -> dict:
    """Timing breakdown of the executor's last query, fed by its query
    span (``executor.last_query_span``).  Falls back to the legacy
    ``last_timings`` dict for executors that predate spans (or when
    tracing is disabled), so callers see the same key vocabulary either
    way: compile_ms / execute_ms / materialize_ms / bytes_scanned /
    scan_gbps / roofline_frac / roofline_peak_gbps / staged_programs.
    Executors without timings (the CPU oracle) yield {}."""
    root = getattr(executor, "last_query_span", None)
    if root:
        t = trace.timings_from_span(root)
        if t:
            return t
    return dict(getattr(executor, "last_timings", None) or {})
