"""Compiler-truth cost ledger: XLA cost/memory analysis per query.

"Query Processing on Tensor Computation Runtimes" (PAPERS.md) makes
the case that accelerator benchmark numbers are only interpretable
next to operator-level cost accounting; this engine's roofline column
rode a hand-rolled ``ops_est`` instead.  This module is the
compiler-truth replacement: every program the engine compiles or
loads (device, sharded, chunkscan, compact, staged subs — all funnel
through ``cache/aot.py``) has its ``compiled.cost_analysis()`` (flops,
bytes accessed, transcendentals) and ``memory_analysis()``
(temp/argument/output bytes) extracted ONCE and attached to the
executable, and every DISPATCH records those numbers into a per-query
ledger the power loop reads out into the BenchReport ``cost`` block.

Recording happens at dispatch, not at compile: warmup compiles run
before the per-query ledger reset, so a compile-time-only hook would
leave every warm in-process query with an empty block.  Warm
AOT-cache hits carry their cost dict inside the cache payload and
manifest (``cache/aot.py`` persists it), so a ``compile_ms=0`` run
still bills compiler-truth numbers — extraction on a deserialized
executable is a fallback, not the design.

Per-dispatch semantics: flops/bytes/transcendentals SUM over
dispatches (a 40-chunk scan costs 40x its program), memory sizes MAX
(concurrency aside, temp arenas are per-dispatch peaks, not
cumulative).  Overflow-retry re-dispatches bill again, matching the
wall-clock they consume.

``cross_check()`` reconciles the block against PR 8's hand-rolled
``ops_est``: a flops/ops ratio outside a generous sanity corridor
flags ``ops_est_drift`` so the legacy estimator can't silently rot.

``platform_peaks()`` is the per-platform peak table behind analyze's
predicted-time model: env override, then measured numbers from
``ndsperf --calibrate`` (``configs/platform_peaks.json``), then the
datasheet builtins.  Pure host-side lookups — this module NEVER
initializes a jax backend (the utils/report.py dead-tunnel rule).
"""

from __future__ import annotations

import json
import os

from nds_tpu.analysis import locksan

_LOCK = locksan.lock("obs.costs._LOCK")

# normalized cost-dict keys and how the ledger folds them per dispatch
_SUM_KEYS = ("flops", "bytes_accessed", "transcendentals")
_MAX_KEYS = ("temp_bytes", "argument_bytes", "output_bytes")

# XLA cost_analysis() vocabulary -> our normalized keys (the XLA keys
# contain spaces; some backends report sentinel negatives — dropped)
_COST_KEYS = {"flops": "flops", "bytes accessed": "bytes_accessed",
              "transcendentals": "transcendentals"}

# memory_analysis() attributes -> normalized keys
_MEM_ATTRS = {"temp_size_in_bytes": "temp_bytes",
              "argument_size_in_bytes": "argument_bytes",
              "output_size_in_bytes": "output_bytes"}

# datasheet peak dense FLOP/s (f32-ish sustained, not marketing bf16
# numbers) keyed by device_kind prefix; the bandwidth twin lives in
# engine/device_exec._PEAK_MEM_GBPS. Calibrated measurements from
# ``ndsperf --calibrate`` override both (see platform_peaks()).
_PEAK_FLOPS = {"tpu v4": 275e12, "tpu v5 lite": 197e12,
               "tpu v5e": 197e12, "tpu v5": 459e12,
               "tpu v6 lite": 918e12, "cpu": 5e10}
_PEAK_MEM_GBPS = {"tpu v4": 1228.0, "tpu v5 lite": 819.0,
                  "tpu v5e": 819.0, "tpu v5": 2765.0,
                  "tpu v6 lite": 1640.0, "cpu": 25.0}

PEAKS_ENV = "NDS_TPU_PLATFORM_PEAKS"
PEAKS_BASENAME = os.path.join("configs", "platform_peaks.json")

# sanity corridor for compiler-flops vs hand-rolled ops_est: the
# estimator counts logical column ops, the compiler counts fused HLO
# flops — they disagree by fusion and padding factors, not by orders
# of magnitude beyond these
DRIFT_CORRIDOR = (0.1, 10000.0)


# ------------------------------------------------------------ extraction

def compute_cost(compiled) -> "dict | None":
    """Normalized cost dict straight off a jax.stages.Compiled, or
    None when the backend exposes neither analysis. Never raises —
    cost accounting must not fail a query."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: list per device
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            for xla_key, key in _COST_KEYS.items():
                v = ca.get(xla_key)
                if isinstance(v, (int, float)) and v > 0:
                    out[key] = float(v)
    except Exception:  # noqa: BLE001 - analysis is best-effort
        pass
    try:
        ma = compiled.memory_analysis()
        for attr, key in _MEM_ATTRS.items():
            v = getattr(ma, attr, None)
            if isinstance(v, (int, float)) and v > 0:
                out[key] = int(v)
    except Exception:  # noqa: BLE001 - analysis is best-effort
        pass
    return out or None


def attach(compiled, cost: "dict | None") -> None:
    """Pin a (possibly store-served) cost dict onto the executable so
    dispatch-time extraction is a dict read. Best-effort: some stages
    objects reject attributes — extract() just recomputes then."""
    if not isinstance(cost, dict):
        return
    try:
        setattr(compiled, "_nds_cost", dict(cost))
    except Exception:  # noqa: BLE001 - frozen object: memo is optional
        pass


def extract(compiled) -> "dict | None":
    """Memoized cost dict for an executable: the attached copy when a
    compile/load site already paid for it, else computed and attached
    here."""
    cost = getattr(compiled, "_nds_cost", None)
    if isinstance(cost, dict):
        return cost
    cost = compute_cost(compiled)
    if cost is not None:
        attach(compiled, cost)
    return cost


def _device_kind() -> "str | None":
    """Lowercased device_kind of the live backend, or None. NEVER
    initializes a backend (memwatch's rule: discovery can block
    forever on a dead chip tunnel), and never initiates the jax import
    (memwatch's thread-safety rule)."""
    import sys
    mod = sys.modules.get("jax")
    if mod is None or getattr(getattr(mod, "__spec__", None),
                              "_initializing", False):
        return None
    try:
        import jax
        from jax._src import xla_bridge as _xb
        if not getattr(_xb, "_backends", None):
            return None
        return str(jax.devices()[0].device_kind).lower()
    except Exception:  # noqa: BLE001 - gauge must never fail a query
        return None


# ---------------------------------------------------------------- ledger

# obs.costs.enabled (default on): the ledger's only knob. Dispatch
# hooks check it so a disabled run pays one predicate per dispatch and
# emits no cost block at all (summaries keep their pre-cost shape)
_ENABLED = True


def set_enabled(flag: bool) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


def enabled() -> bool:
    return _ENABLED


def configure_from(config=None) -> None:
    """Apply ``obs.costs.enabled`` from an EngineConfig (the power
    loop's entry point, next to telemetry.start_from_config)."""
    if config is None:
        set_enabled(True)
        return
    try:
        set_enabled(config.get_bool("obs.costs.enabled", True))
    except Exception:  # noqa: BLE001 - config typo: ledger stays on
        set_enabled(True)


class CostLedger:
    """Per-query accumulator the executors feed at every program
    dispatch; read out once per query by the power loop."""

    def __init__(self) -> None:
        self._sums: dict = {}
        self._maxes: dict = {}
        self._programs: dict = {}

    def reset_query(self) -> None:
        with _LOCK:
            self._sums = {}
            self._maxes = {}
            self._programs = {}

    def record(self, kind: str, cost: "dict | None") -> None:
        """Bill one dispatch of one program. ``cost=None`` (backend
        without analyses) still counts the program so the block's
        ``programs`` census stays truthful."""
        if not _ENABLED:
            return
        with _LOCK:
            self._programs[kind] = self._programs.get(kind, 0) + 1
            if not cost:
                return
            for k in _SUM_KEYS:
                v = cost.get(k)
                if v:
                    self._sums[k] = self._sums.get(k, 0.0) + float(v)
            for k in _MAX_KEYS:
                v = cost.get(k)
                if v and v > self._maxes.get(k, 0):
                    self._maxes[k] = int(v)

    def query_block(self) -> "dict | None":
        """BenchReport ``cost`` block, or None when the query
        dispatched no tracked programs (harness-only paths, the CPU
        oracle)."""
        with _LOCK:
            if not self._programs:
                return None
            block: dict = {k: float(self._sums.get(k, 0.0))
                           for k in _SUM_KEYS}
            for k in _MAX_KEYS:
                if k in self._maxes:
                    block[k] = self._maxes[k]
            block["programs"] = dict(self._programs)
        kind = _device_kind()
        if kind:
            block["platform"] = kind
        return block


LEDGER = CostLedger()


def reset_query() -> None:
    LEDGER.reset_query()


def record(kind: str, cost: "dict | None") -> None:
    LEDGER.record(kind, cost)


def record_program(kind: str, compiled) -> None:
    """The executor dispatch hook: extract (memoized) + bill."""
    if not _ENABLED:
        return
    LEDGER.record(kind, extract(compiled))


def query_block() -> "dict | None":
    return LEDGER.query_block()


# ----------------------------------------------------------- cross-check

def cross_check(block: "dict | None",
                ops_est: "float | None") -> "dict | None":
    """Reconcile the compiler-truth block against the hand-rolled
    ``ops_est`` roofline input (PR 8). Adds ``ops_est`` /
    ``flops_per_op`` and flags ``ops_est_drift`` when the ratio falls
    outside DRIFT_CORRIDOR — either estimator rotting shows up in the
    summary instead of silently skewing the roofline column."""
    if block is None:
        return None
    out = dict(block)
    try:
        ops = float(ops_est) if ops_est else 0.0
    except (TypeError, ValueError):
        ops = 0.0
    flops = out.get("flops") or 0.0
    if ops > 0 and flops > 0:
        ratio = flops / ops
        out["ops_est"] = ops
        out["flops_per_op"] = ratio
        lo, hi = DRIFT_CORRIDOR
        if not lo <= ratio <= hi:
            out["ops_est_drift"] = True
    return out


# -------------------------------------------------------- platform peaks

_calibrated_cache: "tuple | None" = None  # (path, mtime, dict)


def peaks_path() -> str:
    """Where ``ndsperf --calibrate`` writes and this module reads the
    measured per-platform peaks (env NDS_TPU_PLATFORM_PEAKS
    overrides; default: configs/platform_peaks.json at the repo
    root)."""
    env = os.environ.get(PEAKS_ENV)
    if env:
        return env
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, PEAKS_BASENAME)


def calibrated_peaks() -> dict:
    """The measured peaks file as ``{device_kind: {"flops": F,
    "mem_gbps": B}}``, mtime-cached; {} when absent/unreadable."""
    global _calibrated_cache
    path = peaks_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return {}
    with _LOCK:
        if (_calibrated_cache is not None
                and _calibrated_cache[0] == path
                and _calibrated_cache[1] == mtime):
            return _calibrated_cache[2]
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, ValueError):
        data = {}
    data = {str(k).lower(): v for k, v in data.items()
            if isinstance(v, dict)}
    with _LOCK:
        _calibrated_cache = (path, mtime, data)
    return data


def _prefix_lookup(table: dict, kind: str):
    """Longest device-kind prefix match (the device_exec idiom):
    "tpu v5 lite" must beat "tpu v5" for a "TPU v5 lite" device."""
    kind = (kind or "").lower()
    for prefix, val in sorted(table.items(),
                              key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return val
    return None


def platform_peaks(kind: "str | None") -> "dict | None":
    """Peak ``{"flops": FLOP/s, "mem_gbps": GB/s}`` for a device kind:
    calibrated measurements (ndsperf --calibrate) win over the
    datasheet builtins, per key. None when the platform is unknown to
    both."""
    if not kind:
        return None
    kind = kind.lower()
    measured = _prefix_lookup(calibrated_peaks(), kind) or {}
    flops = measured.get("flops")
    gbps = measured.get("mem_gbps")
    if not isinstance(flops, (int, float)) or flops <= 0:
        flops = _prefix_lookup(_PEAK_FLOPS, kind)
    if not isinstance(gbps, (int, float)) or gbps <= 0:
        gbps = _prefix_lookup(_PEAK_MEM_GBPS, kind)
    if not flops and not gbps:
        return None
    out = {}
    if flops:
        out["flops"] = float(flops)
    if gbps:
        out["mem_gbps"] = float(gbps)
    return out


def calibrated_mem_gbps(kind: "str | None") -> "float | None":
    """Measured memory bandwidth for a device kind, or None — the
    hook device_exec._peak_mem_gbps() consults between its env
    override and the builtin table."""
    if not kind:
        return None
    measured = _prefix_lookup(calibrated_peaks(), kind.lower())
    if isinstance(measured, dict):
        v = measured.get("mem_gbps")
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def predicted_ms(block: "dict | None") -> "float | None":
    """Roofline-model predicted execute time for a query's cost block:
    max(flops/peak_flops, bytes/peak_bw), in ms. None when the block
    or its platform's peaks are missing — callers render a blank
    column, never a guess."""
    if not isinstance(block, dict):
        return None
    peaks = platform_peaks(block.get("platform"))
    if not peaks:
        return None
    flops = block.get("flops") or 0.0
    nbytes = block.get("bytes_accessed") or 0.0
    t_flops = (flops / peaks["flops"]) if peaks.get("flops") else 0.0
    t_bytes = ((nbytes / (peaks["mem_gbps"] * 1e9))
               if peaks.get("mem_gbps") else 0.0)
    t = max(t_flops, t_bytes)
    return t * 1000.0 if t > 0 else None
