"""Per-query device-memory high-water-mark tracking.

"Large Scale Distributed Linear Algebra With Tensor Processing Units"
(PAPERS.md) plans its whole decomposition around explicit per-chip
memory budgets; this engine had no per-query memory signal at all — an
OOM was the first and only indication a query was near the edge.  This
module is the gauge: a process-global tracker the executors feed, read
out once per query by the power loop into the BenchReport ``memory``
block (``{"device_hwm_bytes": int, "source": "device"|"accounted"}``).

Two signal sources, best available wins:

- **Device stats** (``source="device"``): ``jax`` device
  ``memory_stats()["bytes_in_use"]`` summed across addressable
  devices, sampled at the bracketing points the executors already own
  (post-dispatch, post-materialize).  Only consulted when the jax
  backend is ALREADY initialized — the reporter's rule (utils/report.py)
  that observability must never force platform discovery (a dead
  remote-TPU tunnel blocks forever) applies here too.
- **Live-buffer accounting** (``source="accounted"``): executors
  ``add_live``/``sub_live`` the bytes they upload (scan buffers, chunk
  windows); the high-water mark is the max concurrent total.  This is
  the fallback on backends without allocator stats (CPU, virtual mesh)
  and the only signal the pure-pandas CPU oracle has.

The HWM is monotone within a query and resets between queries
(``reset_query()`` in the power loop); the current value also lands on
the ``device_hwm_bytes`` metrics gauge so live snapshots
(obs/snapshot.py) expose it mid-run.
"""

from __future__ import annotations

from nds_tpu.analysis import locksan

_LOCK = locksan.lock("obs.memwatch._LOCK")


def table_bytes(table) -> int:
    """Host-side byte size of a HostTable (values + null masks) — the
    unit of live-buffer accounting for executors that never upload."""
    total = 0
    for c in table.columns.values():
        total += c.values.nbytes
        if c.null_mask is not None:
            total += c.null_mask.nbytes
    return total


def _device_bytes_in_use() -> int | None:
    """Sum of ``bytes_in_use`` across already-initialized jax devices,
    or None when stats are unavailable. NEVER initializes a backend
    (the utils/report.py rule: discovery can block forever on a dead
    chip tunnel) — and never INITIATES the jax import either: the
    telemetry sampler (obs/telemetry.py) calls this from a daemon
    thread, and a thread-side ``import jax`` racing the main thread's
    first import deadlock-breaks into partially-initialized modules."""
    import sys
    mod = sys.modules.get("jax")
    if mod is None or getattr(getattr(mod, "__spec__", None),
                              "_initializing", False):
        return None
    try:
        import jax
        from jax._src import xla_bridge as _xb
        if not getattr(_xb, "_backends", None):
            return None
        total, seen = 0, False
        for d in jax.devices():
            stats = getattr(d, "memory_stats", None)
            stats = stats() if callable(stats) else None
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                seen = True
        return total if seen else None
    except Exception:  # noqa: BLE001 - gauge must never fail a query
        return None


class MemoryTracker:
    """Monotone-within-query high-water mark over both signal
    sources."""

    def __init__(self) -> None:
        self._live = 0          # accounted live-buffer bytes
        self._hwm = 0
        self._source = "accounted"

    # ------------------------------------------------------- accounting

    def reset_query(self) -> None:
        """Start a fresh query window. Accounted live bytes CARRY OVER
        (session-pooled scan buffers outlive queries); only the
        high-water mark resets — to the current live level, so the new
        query's HWM reflects what is resident while IT runs."""
        with _LOCK:
            self._hwm = self._live
            self._source = "accounted"
            self._publish()

    def add_live(self, nbytes: float) -> None:
        with _LOCK:
            self._live += int(nbytes)
            if self._live > self._hwm:
                self._hwm = self._live
                self._publish()

    def sub_live(self, nbytes: float) -> None:
        with _LOCK:
            self._live = max(0, self._live - int(nbytes))

    def sample_device(self) -> None:
        """Fold an allocator reading into the HWM (device stats
        dominate accounting whenever available)."""
        v = _device_bytes_in_use()
        if v is None:
            return
        with _LOCK:
            self._source = "device"
            if v > self._hwm:
                self._hwm = v
                self._publish()

    def _publish(self) -> None:
        # inside _LOCK; the metrics registry has its own lock and never
        # takes this one, so the ordering cannot deadlock
        from nds_tpu.obs import metrics as obs_metrics
        obs_metrics.gauge("device_hwm_bytes").set(self._hwm)

    # ---------------------------------------------------------- readout

    def live(self) -> int:
        """CURRENT usage (not the HWM): allocator ``bytes_in_use`` when
        a jax backend is live, else the accounted live-buffer total —
        the pre-admission signal the scheduler's memory governor
        (engine/scheduler.MemoryGovernor) projects forward before
        dispatching a query."""
        v = _device_bytes_in_use()
        if v is not None:
            return v
        with _LOCK:
            return self._live

    def high_water(self) -> dict | None:
        """BenchReport ``memory`` block, or None when the query touched
        no tracked memory (the harness-only paths)."""
        with _LOCK:
            if self._hwm <= 0:
                return None
            return {"device_hwm_bytes": self._hwm,
                    "source": self._source}


TRACKER = MemoryTracker()


def reset_query() -> None:
    TRACKER.reset_query()


def add_live(nbytes: float) -> None:
    TRACKER.add_live(nbytes)


def sub_live(nbytes: float) -> None:
    TRACKER.sub_live(nbytes)


def sample_device() -> None:
    TRACKER.sample_device()


def live_bytes() -> int:
    return TRACKER.live()


def high_water() -> dict | None:
    return TRACKER.high_water()
