"""NDS-H Throughput Run: N concurrent query streams.

The reference does this with xargs -P spawning one spark-submit per
stream (`nds/nds-throughput:23`). Here each stream is one subprocess
running the power driver (process isolation keeps per-stream XLA compile
caches and HBM pools independent — the analog of per-stream Spark apps),
and the throughput elapse is max(end) - min(start) rounded up to 0.1 s
(`nds/nds_bench.py:138-157,207-208`).
"""

from __future__ import annotations

import argparse
import math
import os
import subprocess
import sys
import time


def run_streams(data_dir: str, stream_paths: list[str], out_dir: str,
                backend: str = "tpu",
                input_format: str = "parquet") -> tuple[float, list[int]]:
    """Launch one power-run subprocess per stream; returns
    (throughput_elapse_seconds, per-stream exit codes)."""
    os.makedirs(out_dir, exist_ok=True)
    procs = []
    start = time.time()
    for sp in stream_paths:
        name = os.path.splitext(os.path.basename(sp))[0]
        tlog = os.path.join(out_dir, f"{name}_time.csv")
        cmd = [sys.executable, "-m", "nds_tpu.nds_h.power",
               data_dir, sp, tlog, "--backend", backend,
               "--input_format", input_format]
        from nds_tpu.utils.power_core import subprocess_env
        procs.append(subprocess.Popen(cmd, env=subprocess_env(backend)))
    codes = [p.wait() for p in procs]
    elapse = time.time() - start
    # round up to 0.1 s, the reference's Ttt granularity
    elapse = math.ceil(elapse * 10) / 10.0
    return elapse, codes


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="NDS-H throughput run")
    p.add_argument("data_dir")
    p.add_argument("streams", nargs="+", help="stream_N.sql files")
    p.add_argument("--out_dir", default="throughput_logs")
    p.add_argument("--backend", choices=["tpu", "cpu"], default="tpu")
    p.add_argument("--input_format", choices=["parquet", "raw"],
                   default="parquet")
    args = p.parse_args(argv)
    elapse, codes = run_streams(args.data_dir, args.streams, args.out_dir,
                                args.backend, args.input_format)
    print(f"Throughput Time: {elapse} s over {len(args.streams)} streams")
    sys.exit(1 if any(codes) else 0)


if __name__ == "__main__":
    main()
