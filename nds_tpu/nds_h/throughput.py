"""NDS-H Throughput Run: N concurrent query streams.

The reference does this with xargs -P spawning one spark-submit per
stream (`nds/nds-throughput:23`). Here each stream is one subprocess
running the power driver (process isolation keeps per-stream XLA compile
caches and HBM pools independent — the analog of per-stream Spark apps),
and the throughput elapse is max(end) - min(start) rounded up to 0.1 s
(`nds/nds_bench.py:138-157,207-208`).

Streams run SUPERVISED exactly like the NDS fleet
(resilience/supervise.py, spec plumbing shared via
nds_tpu.nds.throughput._stream_specs): heartbeat liveness through the
per-stream snapshot file, kill + restart-once on stall with
``--stall_s``, and a ``throughput_summary.json`` recording exit codes,
signals, stalls and restarts.
"""

from __future__ import annotations

import argparse
import math
import os
import sys


def run_streams(data_dir: str, stream_paths: list[str], out_dir: str,
                backend: str = "tpu",
                input_format: str = "parquet",
                stall_s: float | None = None,
                max_restarts: int | None = None
                ) -> tuple[float, list[int]]:
    """Launch one supervised power-run subprocess per stream; returns
    (throughput_elapse_seconds, per-stream final exit codes)."""
    from nds_tpu.nds.throughput import _stream_specs
    from nds_tpu.nds_h.streams import parse_query_stream
    from nds_tpu.resilience.supervise import (
        StreamSupervisor, describe_summary,
    )
    os.makedirs(out_dir, exist_ok=True)
    specs = _stream_specs(data_dir, stream_paths, out_dir, backend,
                          input_format, False,
                          "nds_tpu.nds_h.power", parse_query_stream)
    # restarts only with the heartbeat plumbing stall_s arms (see
    # nds_tpu.nds.throughput.run_streams)
    if max_restarts is None:
        max_restarts = 1 if stall_s else 0
    sup = StreamSupervisor(specs, out_dir, stall_s=stall_s,
                           max_restarts=max_restarts)
    elapse, codes, summary = sup.run()
    print(describe_summary(summary))
    # round up to 0.1 s, the reference's Ttt granularity
    elapse = math.ceil(elapse * 10) / 10.0
    return elapse, codes


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="NDS-H throughput run")
    p.add_argument("data_dir")
    p.add_argument("streams", nargs="+", help="stream_N.sql files")
    p.add_argument("--out_dir", default="throughput_logs")
    p.add_argument("--backend", choices=["tpu", "cpu"], default="tpu")
    p.add_argument("--input_format", choices=["parquet", "raw"],
                   default="parquet")
    p.add_argument("--stall_s", type=float, default=None,
                   help="supervise streams: kill on heartbeat stall "
                        "past this budget, restart once (README "
                        "Resilience)")
    p.add_argument("--max_restarts", type=int, default=None,
                   help="restart budget per supervised stream (default "
                        "1 when --stall_s is set; graceful-drain exits "
                        "75 resume without charging it)")
    args = p.parse_args(argv)
    elapse, codes = run_streams(args.data_dir, args.streams, args.out_dir,
                                args.backend, args.input_format,
                                stall_s=args.stall_s,
                                max_restarts=args.max_restarts)
    print(f"Throughput Time: {elapse} s over {len(args.streams)} streams")
    sys.exit(1 if any(codes) else 0)


if __name__ == "__main__":
    main()
