"""NDS-H data generation driver.

Behavioral port of `nds-h/nds_h_gen_data.py`: emit the 8 TPC-H tables as
'|'-delimited chunk files under per-table directories, with dbgen's
chunking contract (`-C parallel -S step`, `nds-h/nds_h_gen_data.py:90-95`)
and the nation/region single-file special case (`:109-115`).

Two generation paths:
- ``--use_builtin`` (default): the hermetic numpy generator
  (`nds_tpu.datagen.tpch`) fanned out over a process pool — the
  replacement for the reference's Hadoop-MR GenTable driver
  (`nds-h/tpch-gen/.../GenTable.java:209-277`); each (table, chunk) is an
  independent task, so the same fan-out runs across hosts.
- external dbgen via ``--dbgen_path``: shells out to the TPC-licensed
  tool exactly like the reference (the tool stays external, SURVEY.md
  §2.4 licensing note).
"""

from __future__ import annotations

import argparse
import os
import subprocess
from concurrent.futures import ProcessPoolExecutor

from nds_tpu.datagen import tpch
from nds_tpu.io.csv_io import write_tbl
from nds_tpu.nds_h.schema import get_schemas

SOURCE_TABLES = ["customer", "lineitem", "nation", "orders", "part",
                 "partsupp", "region", "supplier"]
SINGLE_CHUNK_TABLES = {"nation", "region"}


def _gen_chunk(table: str, sf: float, parallel: int, step: int,
               out_dir: str) -> str:
    arrays = tpch.gen_table(table, sf, parallel, step)
    schemas = get_schemas()
    if table in SINGLE_CHUNK_TABLES or parallel == 1:
        path = os.path.join(out_dir, table, f"{table}.tbl")
    else:
        path = os.path.join(out_dir, table, f"{table}.tbl.{step}")
    write_tbl(arrays, schemas[table], path)
    return path


def generate_data_local(scale: float, parallel: int, data_dir: str,
                        overwrite: bool = False, table: str | None = None,
                        chunk_range: tuple[int, int] | None = None,
                        workers: int | None = None) -> list[str]:
    if os.path.isdir(data_dir) and os.listdir(data_dir) and not overwrite:
        raise SystemExit(
            f"data dir {data_dir!r} is not empty (pass --overwrite_output)")
    os.makedirs(data_dir, exist_ok=True)
    tables = [table] if table else SOURCE_TABLES
    lo, hi = chunk_range or (1, parallel)
    tasks = []
    for t in tables:
        if t in SINGLE_CHUNK_TABLES:
            if lo == 1:  # fixed tables generated once, by chunk 1's owner
                tasks.append((t, scale, 1, 1, data_dir))
            continue
        for step in range(lo, hi + 1):
            tasks.append((t, scale, parallel, step, data_dir))
    paths = []
    with ProcessPoolExecutor(max_workers=workers) as pool:
        for p in pool.map(_gen_chunk_star, tasks):
            paths.append(p)
    return paths


def _gen_chunk_star(args):
    return _gen_chunk(*args)


def generate_data_dbgen(scale: int, parallel: int, data_dir: str,
                        dbgen_path: str) -> None:
    """External-tool path: one dbgen process per chunk (the reference's
    per-mapper command, `GenTable.java:209-277`, without Hadoop)."""
    os.makedirs(data_dir, exist_ok=True)
    procs = []
    env = dict(os.environ, DSS_PATH=data_dir)
    for step in range(1, parallel + 1):
        cmd = [dbgen_path, "-s", str(scale), "-C", str(parallel),
               "-S", str(step), "-f"]
        procs.append(subprocess.Popen(cmd, env=env,
                                      cwd=os.path.dirname(dbgen_path)))
    rc = [p.wait() for p in procs]
    if any(rc):
        raise SystemExit(f"dbgen chunks failed: {rc}")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="generate NDS-H raw data")
    p.add_argument("scale", type=float, help="scale factor")
    p.add_argument("parallel", type=int, help="number of chunks")
    p.add_argument("data_dir", help="output directory")
    p.add_argument("--table", choices=SOURCE_TABLES)
    p.add_argument("--range", dest="chunk_range",
                   help="'first,last' 1-based chunk subrange to (re)generate")
    p.add_argument("--overwrite_output", action="store_true")
    p.add_argument("--dbgen_path",
                   help="use the external TPC dbgen binary instead of the "
                        "builtin generator")
    p.add_argument("--workers", type=int,
                   help="process-pool size (default: cpu count)")
    args = p.parse_args(argv)
    if args.dbgen_path:
        generate_data_dbgen(int(args.scale), args.parallel, args.data_dir,
                            args.dbgen_path)
        return
    rng = None
    if args.chunk_range:
        lo, hi = (int(x) for x in args.chunk_range.split(","))
        if not (1 <= lo <= hi <= args.parallel):
            raise SystemExit(f"invalid --range {args.chunk_range!r}")
        rng = (lo, hi)
    generate_data_local(args.scale, args.parallel, args.data_dir,
                        args.overwrite_output, args.table, rng,
                        args.workers)


if __name__ == "__main__":
    main()
