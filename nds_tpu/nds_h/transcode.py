"""NDS-H Load Test: raw '|'-delimited text -> columnar Parquet warehouse.

Behavioral port of `nds-h/nds_h_transcode.py` (and the report format of
`nds/nds_transcode.py:205-229`): per-table transcode timing, a plain-text
report with per-table seconds + Total time, and the load-end timestamp the
orchestrator uses as the stream RNGSEED (`nds/nds_transcode.py:210-216` ->
`nds/nds_bench.py:60-74`).

TPU-native: output is Parquet with dictionary-encoded strings whose
dictionaries are re-sorted on read (`nds_tpu/io/csv_io.py`), which is the
layout the device engine uploads to HBM. Partitioned output writes one
file per input chunk so multi-host loaders can shard by file.
"""

from __future__ import annotations

import argparse
import os
import time

from nds_tpu.io import csv_io
from nds_tpu.nds_h.schema import get_schemas


def transcode_table(name, schema, input_dir: str, output_dir: str,
                    compression: str = "snappy",
                    output_format: str = "parquet") -> float:
    t0 = time.perf_counter()
    tdir = os.path.join(input_dir, name)
    if os.path.isdir(tdir):
        from nds_tpu.io.integrity import MANIFEST_NAME
        paths = sorted(os.path.join(tdir, f) for f in os.listdir(tdir)
                       if not f.startswith(".") and f != MANIFEST_NAME)
    else:
        single = os.path.join(input_dir, f"{name}.tbl")
        paths = [single]
    table = csv_io.read_tbl(paths, name, schema)
    ext = csv_io.FORMAT_EXT[output_format]
    out = os.path.join(output_dir, name, f"part-0{ext}")
    csv_io.write_table(table, out, output_format, compression=compression)
    # per-table digest manifest for verified loads (io/integrity.py)
    from nds_tpu.io import integrity
    integrity.write_manifest(os.path.join(output_dir, name))
    return time.perf_counter() - t0


def transcode(input_dir: str, output_dir: str, report_path: str,
              tables: list[str] | None = None,
              compression: str = "snappy",
              output_format: str = "parquet") -> dict:
    schemas = get_schemas()
    if tables:
        unknown = set(tables) - set(schemas)
        if unknown:
            raise ValueError(f"unknown tables: {sorted(unknown)}")
        schemas = {t: schemas[t] for t in tables}
    os.makedirs(output_dir, exist_ok=True)
    timings = {}
    for name, schema in schemas.items():
        timings[name] = transcode_table(
            name, schema, input_dir, output_dir, compression,
            output_format)
        print(f"Time taken: {timings[name]:.3f} s for table {name}")
    load_end = int(time.time())
    report = ["Total conversion time for %d tables was %.3fs" % (
        len(timings), sum(timings.values()))]
    for name, secs in timings.items():
        report.append("Time to convert '%s' was %.4fs" % (name, secs))
    report.append("")
    # the stream-seed contract: RNGSEED = load end timestamp
    report.append(f"RNGSEED used: {load_end}")
    os.makedirs(os.path.dirname(report_path) or ".", exist_ok=True)
    with open(report_path, "w") as f:
        f.write("\n".join(report) + "\n")
    return timings


# anchored report parsing, shared with NDS (`nds/nds_bench.py:60-89`)
from nds_tpu.utils.loadreport import get_load_time, get_rngseed  # noqa: E402,F401


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="NDS-H load test: raw text -> Parquet warehouse")
    p.add_argument("input_dir", help="raw data directory (datagen output)")
    p.add_argument("output_dir", help="Parquet warehouse directory")
    p.add_argument("report_file", help="load-report text file")
    p.add_argument("--tables", nargs="+", help="subset of tables")
    p.add_argument("--compression", default="snappy")
    p.add_argument("--output_format", default="parquet",
                   choices=["parquet", "orc", "json", "avro"],
                   help="warehouse file format "
                        "(`nds/nds_transcode.py:69-152`; avro via the "
                        "built-in container codec, io/avro_io.py)")
    args = p.parse_args(argv)
    transcode(args.input_dir, args.output_dir, args.report_file,
              args.tables, args.compression,
              output_format=args.output_format)


if __name__ == "__main__":
    main()
