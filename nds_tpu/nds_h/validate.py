"""NDS-H output validation: diff two power runs' saved query outputs.

Behavioral port of `nds-h/nds_h_validate.py`: per query, row-count check
then row-by-row compare with epsilon on float/decimal columns
(`nds/nds_validate.py:166-192` math.isclose semantics), optional
order-insensitive mode that sorts both sides (`:130-131`), the NDS-H
skips (query15_part1/3 never produce comparable output,
`nds-h/nds_h_validate.py:48-51`) and the q18 non-deterministic column
drop (`:52-54`). Exit status mirrors the reference: 0 only if every
compared query matches.
"""

from __future__ import annotations

import argparse
import math
import os
import sys

import numpy as np
import pandas as pd

from nds_tpu.io.result_io import read_result
from nds_tpu.nds_h import streams

SKIP_QUERIES = {"query15_part1", "query15_part3"}
# q18: o_orderkey ties at the LIMIT 100 edge make that column's row
# content non-deterministic between engines (reference drops it,
# `nds-h/nds_h_validate.py:52-54`); positional index 2 in the template
SKIP_COLUMNS = {"query18": [2]}


def compare_results(dir1: str, dir2: str, query_name: str,
                    ignore_ordering: bool = True, epsilon: float = 0.00001,
                    use_iterator: bool = False) -> bool:
    df1 = read_result(os.path.join(dir1, query_name))
    df2 = read_result(os.path.join(dir2, query_name))
    if len(df1) != len(df2):
        print(f"[{query_name}] row count mismatch: "
              f"{len(df1)} vs {len(df2)}")
        return False
    if df1.shape[1] != df2.shape[1]:
        print(f"[{query_name}] column count mismatch: "
              f"{df1.shape[1]} vs {df2.shape[1]}")
        return False
    drop = SKIP_COLUMNS.get(query_name, [])
    if drop:
        keep = [i for i in range(df1.shape[1]) if i not in drop]
        df1 = df1.iloc[:, keep]
        df2 = df2.iloc[:, keep]
    if ignore_ordering:
        df1 = _canon_sort(df1)
        df2 = _canon_sort(df2)
    for i in range(df1.shape[1]):
        a = df1.iloc[:, i]
        b = df2.iloc[:, i]
        if not _col_equal(a, b, epsilon):
            print(f"[{query_name}] column {i} ({df1.columns[i]}) differs")
            return False
    return True


def _canon_sort(df: pd.DataFrame) -> pd.DataFrame:
    if not len(df):
        return df
    keys = {}
    for i, c in enumerate(df.columns):
        col = df.iloc[:, i]
        if col.dtype.kind == "f":
            keys[f"k{i}"] = col.round(4)
        else:
            keys[f"k{i}"] = col.astype(str)
    order = pd.DataFrame(keys).sort_values(list(keys)).index
    return df.loc[order].reset_index(drop=True)


def _col_equal(a: pd.Series, b: pd.Series, epsilon: float) -> bool:
    na, nb = a.isna().to_numpy(), b.isna().to_numpy()
    if not (na == nb).all():
        return False
    a, b = a[~na], b[~nb]
    if a.dtype.kind == "f" or b.dtype.kind == "f":
        fa = pd.to_numeric(a, errors="coerce").to_numpy(dtype=float)
        fb = pd.to_numeric(b, errors="coerce").to_numpy(dtype=float)
        return all(math.isclose(x, y, rel_tol=epsilon)
                   for x, y in zip(fa, fb))
    return list(a.astype(str)) == list(b.astype(str))


def iterate_queries(dir1: str, dir2: str, stream_path: str,
                    ignore_ordering: bool = True,
                    epsilon: float = 0.00001) -> list[str]:
    """Compare every query in the stream; returns names that mismatched."""
    queries = streams.parse_query_stream(stream_path)
    unmatched = []
    for qname in queries:
        if qname in SKIP_QUERIES:
            print(f"=== Skipping {qname} ===")
            continue
        ok = compare_results(dir1, dir2, qname, ignore_ordering, epsilon)
        status = "MATCH" if ok else "MISMATCH"
        print(f"=== Comparing Query: {qname} -> {status} ===")
        if not ok:
            unmatched.append(qname)
    if unmatched:
        print(f"Unmatched queries: {unmatched}")
    return unmatched


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="diff saved query outputs from two power runs")
    p.add_argument("dir1", help="first output_prefix (e.g. CPU oracle run)")
    p.add_argument("dir2", help="second output_prefix (e.g. TPU run)")
    p.add_argument("query_stream", help="stream file both runs executed")
    p.add_argument("--epsilon", type=float, default=0.00001)
    p.add_argument("--ignore_ordering", action="store_true")
    args = p.parse_args(argv)
    unmatched = iterate_queries(args.dir1, args.dir2, args.query_stream,
                                args.ignore_ordering, args.epsilon)
    sys.exit(1 if unmatched else 0)


if __name__ == "__main__":
    main()
