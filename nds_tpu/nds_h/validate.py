"""NDS-H output validation: diff two power runs' saved query outputs.

Behavioral port of `nds-h/nds_h_validate.py` over the shared diff core
(`nds_tpu/utils/validate_core.py`): per query, row-count check then
row-by-row compare with epsilon on float/decimal columns
(`nds/nds_validate.py:166-192` math.isclose semantics), optional
order-insensitive mode that sorts both sides (`:130-131`), the NDS-H
skips (query15_part1/3 never produce comparable output,
`nds-h/nds_h_validate.py:48-51`) and the q18 non-deterministic column
drop (`:52-54`). Exit status mirrors the reference: 0 only if every
compared query matches.
"""

from __future__ import annotations

import argparse
import os
import sys

from nds_tpu.nds_h import streams
from nds_tpu.utils.validate_core import compare_results as _compare_core

SKIP_QUERIES = {"query15_part1", "query15_part3"}
# q18: o_orderkey ties at the LIMIT 100 edge make that column's row
# content non-deterministic between engines (reference drops it,
# `nds-h/nds_h_validate.py:52-54`); positional index 2 in the template
SKIP_COLUMNS = {"query18": [2]}


def compare_results(dir1: str, dir2: str, query_name: str,
                    ignore_ordering: bool = True,
                    epsilon: float = 0.00001,
                    use_iterator: bool = False) -> bool:
    return _compare_core(dir1, dir2, query_name, ignore_ordering,
                         epsilon, skip_columns=SKIP_COLUMNS)


def iterate_queries(dir1: str, dir2: str, stream_path: str,
                    ignore_ordering: bool = True,
                    epsilon: float = 0.00001) -> list[str]:
    """Compare every query in the stream; returns names that mismatched."""
    queries = streams.parse_query_stream(stream_path)
    unmatched = []
    for qname in queries:
        if qname in SKIP_QUERIES:
            print(f"=== Skipping {qname} ===")
            continue
        here1 = os.path.isdir(os.path.join(dir1, qname))
        here2 = os.path.isdir(os.path.join(dir2, qname))
        if not here1 and not here2:
            # subset runs leave most queries without output; loud so a
            # double-crash (both engines failed the query) is visible
            print(f"=== {qname}: no output on either side — "
                  f"not compared ===")
            continue
        if here1 != here2:
            print(f"=== {qname}: output present on only one side ===")
            unmatched.append(qname)
            continue
        ok = compare_results(dir1, dir2, qname, ignore_ordering, epsilon)
        status = "MATCH" if ok else "MISMATCH"
        print(f"=== Comparing Query: {qname} -> {status} ===")
        if not ok:
            unmatched.append(qname)
    if unmatched:
        print(f"Unmatched queries: {unmatched}")
    return unmatched


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="diff saved query outputs from two power runs")
    p.add_argument("dir1", help="first output_prefix (e.g. CPU oracle run)")
    p.add_argument("dir2", help="second output_prefix (e.g. TPU run)")
    p.add_argument("query_stream", help="stream file both runs executed")
    p.add_argument("--epsilon", type=float, default=0.00001)
    p.add_argument("--ignore_ordering", action="store_true")
    args = p.parse_args(argv)
    unmatched = iterate_queries(args.dir1, args.dir2, args.query_stream,
                                args.ignore_ordering, args.epsilon)
    sys.exit(1 if unmatched else 0)


if __name__ == "__main__":
    main()
