"""NDS-H Power Run driver.

Behavioral port of the reference's power driver (`nds-h/nds_h_power.py`)
over the shared power core (`nds_tpu/utils/power_core.py`): parse a
query stream by its ``-- Template file: N`` markers (q15 runs as three
parts: create view / select / drop view, `nds-h/nds_h_power.py:78-82`),
register the 8 tables, run every query in stream order recording
per-query wall-clock ms, emit the CSV time log
(`nds/nds_power.py:294-303` format) and optional per-query JSON
summaries, honor ``--allow_failure`` and the template/property-file
config layers, and exit non-zero if any query failed
(`nds-h/nds_h_power.py:296`).

TPU-native notes:
- "setup tables" = load columnar data host-side and (for the device
  backend) upload columns to HBM once — the analog of temp-view
  registration timing (CreateTempView rows in the time log).
- per-query timing brackets the full execute INCLUDING device->host
  result materialization (results are numpy), so there is no hidden
  async tail — the reference's df.collect() contract.
- ``--warmup`` optionally runs each query once before timing to separate
  XLA compile time from steady-state (compile time is part of the
  benchmark when warmup=0, matching cold Spark JITs). q15's stateful
  view parts are never warmed.
"""

from __future__ import annotations

import argparse
import sys

from nds_tpu.engine.session import Session
from nds_tpu.nds_h import streams
from nds_tpu.nds_h.schema import get_schemas
from nds_tpu.utils import power_core

SUITE = power_core.Suite(
    name="nds_h",
    get_schemas=get_schemas,
    parse_query_stream=streams.parse_query_stream,
    session_for=lambda factory, **kw: Session.for_nds_h(factory),
    raw_ext=".tbl",
    warmup_skip_prefixes=("query15_part",),
)

# back-compat conveniences used by scripts/tests
def load_warehouse(session, data_dir: str, fmt: str = "parquet",
                   tables=None) -> dict:
    return power_core.load_warehouse(SUITE, session, data_dir, fmt, tables)


def make_session(backend: str) -> Session:
    from nds_tpu.utils.config import EngineConfig
    return power_core.make_session(
        SUITE, EngineConfig(overrides={"engine.backend": backend}))


run_one_query = power_core.run_one_query


def run_query_stream(data_dir: str, stream_path: str, time_log_path: str,
                     backend: str = "tpu", input_format: str = "parquet",
                     json_summary_folder: str | None = None,
                     output_prefix: str | None = None,
                     warmup: int = 0, config=None) -> int:
    """Returns the number of failed queries (the driver exits with it)."""
    from nds_tpu.utils.config import EngineConfig
    if config is None:
        config = EngineConfig(overrides={"engine.backend": backend})
    return power_core.run_query_stream(
        SUITE, data_dir, stream_path, time_log_path, config=config,
        input_format=input_format,
        json_summary_folder=json_summary_folder,
        output_prefix=output_prefix, warmup=warmup)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="NDS-H power run on the TPU columnar engine")
    p.add_argument("data_dir", help="warehouse directory (transcode output)")
    p.add_argument("query_stream", help="stream_N.sql file")
    p.add_argument("time_log", help="output CSV time log path")
    p.add_argument("--backend", choices=["tpu", "cpu", "distributed"],
                   default=None,
                   help="overrides engine.backend from template/property "
                        "files (default tpu)")
    p.add_argument("--placement",
                   choices=["device", "sharded", "chunked", "cpu"],
                   default=None,
                   help="pin the initial placement for every query "
                        "(engine.placement.force); default: the "
                        "scheduler's cost model picks per query "
                        "(README 'Placement & degradation')")
    p.add_argument("--input_format",
                   choices=["parquet", "orc", "json", "avro", "raw"],
                   default="parquet")
    p.add_argument("--extra_time_log",
                   help="write a second copy of the CSV time log here "
                        "(`nds/nds_power.py:305-308`)")
    p.add_argument("--json_summary_folder",
                   help="folder for per-query JSON summaries")
    p.add_argument("--output_prefix",
                   help="save each query's result under this directory")
    p.add_argument("--warmup", type=int, default=0,
                   help="untimed runs per query before the timed one")
    p.add_argument("--profile_dir",
                   help="write jax profiler traces for the stream here")
    p.add_argument("--allow_failure", action="store_true",
                   help="exit 0 even when queries failed "
                        "(`nds/nds_power.py:391-393`)")
    p.add_argument("--query_subset", nargs="+",
                   help="run only these query names (supervised-stream "
                        "restarts resume with the remaining subset)")
    p.add_argument("--resume", action="store_true",
                   help="replay completed statements from the run "
                        "dir's query journal and restart mid-stream "
                        "at the next unfinished one (README "
                        "'Preemption & resume')")
    power_core.add_config_args(p)
    args = p.parse_args(argv)
    config = power_core.config_from_args(args)
    if args.placement:
        config.conf["engine.placement.force"] = args.placement
    failures = power_core.run_query_stream(
        SUITE, args.data_dir, args.query_stream, args.time_log,
        config=config, input_format=args.input_format,
        json_summary_folder=args.json_summary_folder,
        output_prefix=args.output_prefix, warmup=args.warmup,
        query_subset=args.query_subset, profile_dir=args.profile_dir,
        extra_time_log=args.extra_time_log, resume=args.resume)
    sys.exit(0 if (args.allow_failure or not failures) else 1)


if __name__ == "__main__":
    main()
