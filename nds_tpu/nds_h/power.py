"""NDS-H Power Run driver.

Behavioral port of the reference's power driver (`nds-h/nds_h_power.py`):
parse a query stream by its ``-- Template file: N`` markers, register the
8 tables, run every query in stream order recording per-query wall-clock
ms, emit the CSV time log (`nds/nds_power.py:294-303` format) and optional
per-query JSON summaries, and exit non-zero if any query failed
(`nds-h/nds_h_power.py:296`).

TPU-native differences:
- "setup tables" = load columnar data host-side and (for the device
  backend) upload columns to HBM once — the analog of temp-view
  registration timing (`nds-h/nds_h_power.py` CreateTempView rows).
- per-query timing brackets the full execute INCLUDING device->host
  result materialization, with jax async dispatch closed out by
  materialization itself (results are numpy), so there is no hidden
  async tail — the reference's df.collect() contract.
- ``--warmup`` optionally runs each query once before timing to separate
  XLA compile time from steady-state (reported either way; compile time
  is part of the benchmark when warmup=0, matching cold Spark JITs).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from nds_tpu.engine.session import Session
from nds_tpu.nds_h import streams
from nds_tpu.nds_h.schema import get_schemas
from nds_tpu.utils.report import BenchReport
from nds_tpu.utils.timelog import TimeLog


def load_warehouse(session: Session, data_dir: str, fmt: str = "parquet",
                   tables: list[str] | None = None) -> dict:
    """Register every table from a warehouse directory; returns
    {table: seconds} setup timings (the CreateTempView analog)."""
    from nds_tpu.io import csv_io
    schemas = get_schemas()
    timings = {}
    for name, schema in schemas.items():
        if tables is not None and name not in tables:
            continue
        t0 = time.perf_counter()
        tdir = os.path.join(data_dir, name)
        if fmt == "parquet":
            if os.path.isdir(tdir):
                paths = sorted(
                    os.path.join(tdir, f) for f in os.listdir(tdir)
                    if f.endswith(".parquet"))
            else:
                paths = [os.path.join(data_dir, f"{name}.parquet")]
            table = csv_io.read_parquet(paths, name, schema)
        elif fmt == "raw":
            if os.path.isdir(tdir):
                paths = sorted(
                    os.path.join(tdir, f) for f in os.listdir(tdir)
                    if not f.startswith("."))
            else:
                paths = [os.path.join(data_dir, f"{name}.tbl")]
            table = csv_io.read_tbl(paths, name, schema)
        else:
            raise ValueError(f"unknown input format {fmt!r}")
        session.register_table(table)
        timings[name] = time.perf_counter() - t0
    return timings


def make_session(backend: str) -> Session:
    if backend == "tpu":
        from nds_tpu.engine.device_exec import make_device_factory
        return Session.for_nds_h(make_device_factory())
    if backend == "cpu":
        return Session.for_nds_h()
    raise ValueError(f"unknown backend {backend!r}")


def run_one_query(session: Session, sql: str, qname: str = "",
                  output_prefix: str | None = None):
    result = session.sql(sql)
    if result is not None and output_prefix:
        from nds_tpu.io.result_io import write_result
        write_result(result, os.path.join(output_prefix, qname))
    return result


def run_query_stream(data_dir: str, stream_path: str, time_log_path: str,
                     backend: str = "tpu", input_format: str = "parquet",
                     json_summary_folder: str | None = None,
                     output_prefix: str | None = None,
                     warmup: int = 0, keep_sc: bool = False) -> int:
    """Returns the number of failed queries (the driver exits with it)."""
    session = make_session(backend)
    app_id = f"nds-tpu-{backend}-{int(time.time())}"
    tlog = TimeLog(app_id)
    total_start = time.perf_counter()

    setup = load_warehouse(session, data_dir, input_format)
    for tname, secs in setup.items():
        tlog.add(f"CreateTempView {tname}", int(secs * 1000))

    queries = streams.parse_query_stream(stream_path)
    if json_summary_folder:
        os.makedirs(json_summary_folder, exist_ok=True)
    failures = 0
    power_start = time.perf_counter()
    for qname, sql in queries.items():
        if warmup and not qname.startswith("query15_part"):
            for _ in range(warmup):
                try:
                    run_one_query(session, sql)
                except Exception:
                    break
        report = BenchReport(qname, {"backend": backend})
        summary = report.report_on(run_one_query, session, sql, qname,
                                   output_prefix)
        elapsed_ms = summary["queryTimes"][-1]
        tlog.add(qname, elapsed_ms)
        print(f"====== Run {qname} ======")
        print(f"Time taken: {elapsed_ms} millis for {qname}")
        if not report.is_success():
            failures += 1
        if json_summary_folder:
            cwd = os.getcwd()
            os.chdir(json_summary_folder)
            try:
                report.write_summary(prefix=f"power-{app_id}")
            finally:
                os.chdir(cwd)
    power_ms = int((time.perf_counter() - power_start) * 1000)
    tlog.add("Power Test Time", power_ms)
    total_ms = int((time.perf_counter() - total_start) * 1000)
    tlog.add("Total Time", total_ms)
    tlog.write(time_log_path)
    print(f"Power Test Time: {power_ms} millis")
    return failures


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="NDS-H power run on the TPU columnar engine")
    p.add_argument("data_dir", help="warehouse directory (transcode output)")
    p.add_argument("query_stream", help="stream_N.sql file")
    p.add_argument("time_log", help="output CSV time log path")
    p.add_argument("--backend", choices=["tpu", "cpu"], default="tpu",
                   help="device engine (tpu/jax) or CPU oracle")
    p.add_argument("--input_format", choices=["parquet", "raw"],
                   default="parquet")
    p.add_argument("--json_summary_folder",
                   help="folder for per-query JSON summaries")
    p.add_argument("--output_prefix",
                   help="save each query's result under this directory")
    p.add_argument("--warmup", type=int, default=0,
                   help="untimed runs per query before the timed one")
    args = p.parse_args(argv)
    failures = run_query_stream(
        args.data_dir, args.query_stream, args.time_log,
        backend=args.backend, input_format=args.input_format,
        json_summary_folder=args.json_summary_folder,
        output_prefix=args.output_prefix, warmup=args.warmup)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
