"""NDS-H (TPC-H v3.0.1-derived) table schemas.

Engine-native equivalent of the reference's PySpark StructType schemas
(`nds-h/nds_h_schema.py:36-148`): 8 tables, money columns DECIMAL(11,2) as
in the reference. The reference appends a trailing ``ignore`` column per
table to swallow dbgen's trailing '|' (`nds-h/nds_h_schema.py:50-61`); here
that is a CSV-reader option (``trailing_delimiter=True``) instead of a
schema entry, so schemas stay semantically clean.

Key domains follow TPC-H: all *key columns are int64 identifiers.
"""

from __future__ import annotations

from nds_tpu.engine.types import (
    DATE, INT32, INT64, Schema, char, decimal, varchar,
)

MONEY = decimal(11, 2)

# Primary keys per table (used by the engine to pick searchsorted PK-FK
# join strategies and by the maintenance/validation layers).
PRIMARY_KEYS = {
    "customer": ["c_custkey"],
    "lineitem": ["l_orderkey", "l_linenumber"],
    "nation": ["n_nationkey"],
    "orders": ["o_orderkey"],
    "part": ["p_partkey"],
    "partsupp": ["ps_partkey", "ps_suppkey"],
    "region": ["r_regionkey"],
    "supplier": ["s_suppkey"],
}


def get_schemas() -> dict[str, Schema]:
    """All 8 TPC-H table schemas, keyed by table name."""
    return {
        "customer": Schema.of(
            ("c_custkey", INT64, False),
            ("c_name", varchar(25), False),
            ("c_address", varchar(40), False),
            ("c_nationkey", INT64, False),
            ("c_phone", char(15), False),
            ("c_acctbal", MONEY, False),
            ("c_mktsegment", char(10), False),
            ("c_comment", varchar(117), False),
        ),
        "lineitem": Schema.of(
            ("l_orderkey", INT64, False),
            ("l_partkey", INT64, False),
            ("l_suppkey", INT64, False),
            ("l_linenumber", INT32, False),
            ("l_quantity", MONEY, False),
            ("l_extendedprice", MONEY, False),
            ("l_discount", MONEY, False),
            ("l_tax", MONEY, False),
            ("l_returnflag", char(1), False),
            ("l_linestatus", char(1), False),
            ("l_shipdate", DATE, False),
            ("l_commitdate", DATE, False),
            ("l_receiptdate", DATE, False),
            ("l_shipinstruct", char(25), False),
            ("l_shipmode", char(10), False),
            ("l_comment", varchar(44), False),
        ),
        "nation": Schema.of(
            ("n_nationkey", INT64, False),
            ("n_name", char(25), False),
            ("n_regionkey", INT64, False),
            ("n_comment", varchar(152), False),
        ),
        "orders": Schema.of(
            ("o_orderkey", INT64, False),
            ("o_custkey", INT64, False),
            ("o_orderstatus", char(1), False),
            ("o_totalprice", MONEY, False),
            ("o_orderdate", DATE, False),
            ("o_orderpriority", char(15), False),
            ("o_clerk", char(15), False),
            ("o_shippriority", INT32, False),
            ("o_comment", varchar(79), False),
        ),
        "part": Schema.of(
            ("p_partkey", INT64, False),
            ("p_name", varchar(55), False),
            ("p_mfgr", char(25), False),
            ("p_brand", char(10), False),
            ("p_type", varchar(25), False),
            ("p_size", INT32, False),
            ("p_container", char(10), False),
            ("p_retailprice", MONEY, False),
            ("p_comment", varchar(23), False),
        ),
        "partsupp": Schema.of(
            ("ps_partkey", INT64, False),
            ("ps_suppkey", INT64, False),
            ("ps_availqty", INT32, False),
            ("ps_supplycost", MONEY, False),
            ("ps_comment", varchar(199), False),
        ),
        "region": Schema.of(
            ("r_regionkey", INT64, False),
            ("r_name", char(25), False),
            ("r_comment", varchar(152), False),
        ),
        "supplier": Schema.of(
            ("s_suppkey", INT64, False),
            ("s_name", char(25), False),
            ("s_address", varchar(40), False),
            ("s_nationkey", INT64, False),
            ("s_phone", char(15), False),
            ("s_acctbal", MONEY, False),
            ("s_comment", varchar(101), False),
        ),
    }


TABLE_NAMES = sorted(get_schemas().keys())
