"""NDS-H query + stream generation and stream parsing.

Plays the role of the reference's qgen wrapper
(`nds-h/nds_h_gen_query_stream.py:57-81`): emits either one query
(``template_number``) or N permuted 22-query streams, each query preceded
by the ``-- Template file: N`` marker the power driver parses (the
reference injects that marker into qgen.c at build time,
`nds-h/tpch-gen/Makefile:47`; here it is written directly).

Parameter substitution follows the public TPC-H v3 spec §2.4 per-query
rules; ``qualification=True`` pins the spec's validation values. The
TPC-licensed qgen can still be used instead via
``nds_tpu.datagen.toolwrap``.
"""

from __future__ import annotations

import os
import random
import re
from collections import OrderedDict

from nds_tpu.datagen.tpch import (
    COLORS, NATIONS, REGIONS, SEGMENTS, SHIPMODES, TYPE_S2, TYPE_S3,
)

TEMPLATE_DIR = os.path.join(os.path.dirname(__file__), "query_templates")
NUM_QUERIES = 22

# spec §2.4 qualification (validation) parameter values
QUALIFICATION = {
    1: {"delta": 90},
    2: {"size": 15, "type": "BRASS", "region": "EUROPE"},
    3: {"segment": "BUILDING", "date": "1995-03-15"},
    4: {"date": "1993-07-01"},
    5: {"region": "ASIA", "date": "1994-01-01"},
    6: {"date": "1994-01-01", "discount": "0.06", "quantity": 24},
    7: {"nation1": "FRANCE", "nation2": "GERMANY"},
    8: {"nation": "BRAZIL", "region": "AMERICA", "type": "ECONOMY ANODIZED STEEL"},
    9: {"color": "green"},
    10: {"date": "1993-10-01"},
    11: {"nation": "GERMANY", "fraction": "0.0001"},
    12: {"shipmode1": "MAIL", "shipmode2": "SHIP", "date": "1994-01-01"},
    13: {"word1": "special", "word2": "requests"},
    14: {"date": "1995-09-01"},
    15: {"date": "1996-01-01", "stream": "0"},
    16: {"brand": "Brand#45", "type": "MEDIUM POLISHED",
         "sizes": "49, 14, 23, 45, 19, 3, 36, 9"},
    17: {"brand": "Brand#23", "container": "MED BOX"},
    18: {"quantity": 300},
    19: {"brand1": "Brand#12", "brand2": "Brand#23", "brand3": "Brand#34",
         "quantity1": 1, "quantity2": 10, "quantity3": 20},
    20: {"color": "forest", "date": "1994-01-01", "nation": "CANADA"},
    21: {"nation": "SAUDI ARABIA"},
    22: {"codes": "'13', '31', '23', '29', '30', '18', '17'"},
}


def _rand_date(rng, start_year, end_year, month=1, day=1, month_range=None):
    y = rng.randint(start_year, end_year)
    m = rng.randint(*month_range) if month_range else month
    return f"{y:04d}-{m:02d}-{day:02d}"


def random_params(template_number: int, rng: random.Random, stream: int) -> dict:
    """Spec §2.4 substitution-parameter distributions."""
    q = template_number
    brand = lambda: f"Brand#{rng.randint(1, 5)}{rng.randint(1, 5)}"
    nation = lambda: rng.choice([n for n, _ in NATIONS])
    if q == 1:
        return {"delta": rng.randint(60, 120)}
    if q == 2:
        return {"size": rng.randint(1, 50), "type": rng.choice(TYPE_S3),
                "region": rng.choice(REGIONS)}
    if q == 3:
        return {"segment": rng.choice(SEGMENTS),
                "date": f"1995-03-{rng.randint(1, 31):02d}"}
    if q == 4:
        return {"date": _rand_date(rng, 1993, 1997, month_range=(1, 10))}
    if q == 5:
        return {"region": rng.choice(REGIONS), "date": _rand_date(rng, 1993, 1997)}
    if q == 6:
        return {"date": _rand_date(rng, 1993, 1997),
                "discount": f"0.0{rng.randint(2, 9)}", "quantity": rng.randint(24, 25)}
    if q == 7:
        n1 = nation()
        n2 = nation()
        while n2 == n1:
            n2 = nation()
        return {"nation1": n1, "nation2": n2}
    if q == 8:
        n, r = rng.choice(NATIONS)
        t = f"{rng.choice(['STANDARD','SMALL','MEDIUM','LARGE','ECONOMY','PROMO'])} " \
            f"{rng.choice(TYPE_S2)} {rng.choice(TYPE_S3)}"
        return {"nation": n, "region": REGIONS[r], "type": t}
    if q == 9:
        return {"color": rng.choice(COLORS)}
    if q == 10:
        # spec 2.4.10: first of a month, 1993-02 .. 1995-01 (24 months)
        total = rng.randint(0, 23)
        y, m0 = divmod(total + 1, 12)
        return {"date": f"{1993 + y:04d}-{m0 + 1:02d}-01"}
    if q == 11:
        return {"nation": nation(), "fraction": "0.0001"}
    if q == 12:
        m1 = rng.choice(SHIPMODES)
        m2 = rng.choice([m for m in SHIPMODES if m != m1])
        return {"shipmode1": m1, "shipmode2": m2, "date": _rand_date(rng, 1993, 1997)}
    if q == 13:
        return {"word1": rng.choice(["special", "pending", "unusual", "express"]),
                "word2": rng.choice(["packages", "requests", "accounts", "deposits"])}
    if q == 14:
        return {"date": _rand_date(rng, 1993, 1997, month_range=(1, 12))}
    if q == 15:
        return {"date": _rand_date(rng, 1993, 1997, month_range=(1, 10)),
                "stream": str(stream)}
    if q == 16:
        sizes = rng.sample(range(1, 51), 8)
        t = f"{rng.choice(['STANDARD','SMALL','MEDIUM','LARGE','ECONOMY','PROMO'])} " \
            f"{rng.choice(TYPE_S2)}"
        return {"brand": brand(), "type": t, "sizes": ", ".join(map(str, sizes))}
    if q == 17:
        cont = f"{rng.choice(['SM','MED','LG','JUMBO','WRAP'])} " \
               f"{rng.choice(['CASE','BOX','BAG','JAR','PKG','PACK','CAN','DRUM'])}"
        return {"brand": brand(), "container": cont}
    if q == 18:
        return {"quantity": rng.randint(312, 315)}
    if q == 19:
        return {"brand1": brand(), "brand2": brand(), "brand3": brand(),
                "quantity1": rng.randint(1, 10), "quantity2": rng.randint(10, 20),
                "quantity3": rng.randint(20, 30)}
    if q == 20:
        return {"color": rng.choice(COLORS), "date": _rand_date(rng, 1993, 1997),
                "nation": nation()}
    if q == 21:
        return {"nation": nation()}
    if q == 22:
        codes = rng.sample(range(10, 35), 7)
        return {"codes": ", ".join(f"'{c}'" for c in codes)}
    raise ValueError(f"no such template {q}")


def render_query(template_number: int, params: dict | None = None,
                 stream: int = 0) -> str:
    with open(os.path.join(TEMPLATE_DIR, f"q{template_number}.sql")) as f:
        tpl = f.read()
    if params is None:
        params = dict(QUALIFICATION[template_number])
        if template_number == 15:
            params["stream"] = str(stream)
    return tpl.format(**params)


def statements(template_number: int, sql: str | None = None,
               stream: int = 0) -> list[str]:
    """Executable statements of one query. q15 is the multi-statement
    template (create view; select; drop view — the reference runs the
    three parts separately, `nds-h/nds_h_power.py:78-82`); every other
    query is a single statement."""
    if sql is None:
        sql = render_query(template_number, stream=stream)
    if template_number == 15:
        return [s for s in sql.split(";") if s.strip()]
    return [sql]


def stream_order(stream: int, rng_seed: int | None = None) -> list[int]:
    """Query ordering for one stream. Stream 0 (power run) is sequential,
    as with qgen; throughput streams are seeded permutations."""
    order = list(range(1, NUM_QUERIES + 1))
    if stream == 0:
        return order
    rng = random.Random((rng_seed or 0) * 1000 + stream)
    rng.shuffle(order)
    return order


def generate_query_streams(output_dir: str, streams: int,
                           rng_seed: int | None = None,
                           qualification: bool = True) -> list[str]:
    """Write stream_{i}.sql files (reference layout:
    `nds-h/nds_h_gen_query_stream.py:65-76`)."""
    os.makedirs(output_dir, exist_ok=True)
    paths = []
    for i in range(streams):
        rng = random.Random((rng_seed or 0) * 7919 + i)
        parts = []
        for qn in stream_order(i, rng_seed):
            params = None if qualification else random_params(qn, rng, i)
            sql = render_query(qn, params, stream=i)
            parts.append(f"-- Template file: {qn}\n\n{sql}\n")
        path = os.path.join(output_dir, f"stream_{i}.sql")
        with open(path, "w") as f:
            f.write("\n".join(parts))
        paths.append(path)
    return paths


def generate_single_query(output_dir: str, template_number: int,
                          qualification: bool = True,
                          rng_seed: int | None = None) -> str:
    """Write query_{N}.sql (reference: `nds-h/nds_h_gen_query_stream.py:77-81`)."""
    os.makedirs(output_dir, exist_ok=True)
    rng = random.Random(rng_seed or 0)
    params = None if qualification else random_params(template_number, rng, 0)
    path = os.path.join(output_dir, f"query_{template_number}.sql")
    with open(path, "w") as f:
        f.write(f"-- Template file: {template_number}\n\n"
                + render_query(template_number, params) + "\n")
    return path


_MARKER_RE = re.compile(
    r"-- Template file: (\d+)\n\n(.*?)(?=(?:-- Template file: \d+)|\Z)",
    re.DOTALL)


def parse_query_stream(path: str) -> "OrderedDict[str, str]":
    """Stream file -> OrderedDict of {query_name: sql}.

    Reference-compatible: marker regex and the query15 three-part split
    (create view / select / drop view) follow `nds-h/nds_h_power.py:70-87`,
    so the power driver's loop and reports line up query-for-query.
    """
    with open(path) as f:
        stream = f.read()
    queries: "OrderedDict[str, str]" = OrderedDict()
    for num, body in _MARKER_RE.findall(stream):
        if int(num) == 15:
            stmts = [s.strip() for s in body.split(";") if s.strip()]
            if len(stmts) != 3:
                raise ValueError(
                    f"query15 must have 3 statements, found {len(stmts)}")
            for i, s in enumerate(stmts, 1):
                queries[f"query{num}_part{i}"] = s
        else:
            queries[f"query{num}"] = body.strip().rstrip(";")
    return queries
