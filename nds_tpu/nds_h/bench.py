"""NDS-H whole-benchmark orchestrator.

The NDS-H analog of `nds/nds_bench.py:367-498`: run phases in TPC order
as subprocesses (crash isolation by design — state passes via report
files, SURVEY.md §3.4), then compute a composite metric.

Phases: data-gen -> load(transcode) -> stream-gen (RNGSEED = load end
timestamp, `nds/nds_bench.py:60-74`) -> power -> throughput. TPC-H has no
data-maintenance phase (refresh functions exist in TPC-H proper but the
reference's NDS-H suite omits them, `nds-h/` has no maintenance driver),
so the composite is the 3-term geometric form:

    metric = floor(SF * Sq * 22 / (Tpt * Ttt * Tld)^(1/3) / 3600)^-1-ish

mirroring `nds/nds_bench.py:334-357` with the maintenance term dropped.
Config comes from a YAML file like the reference's `nds/bench.yml`.
"""

from __future__ import annotations

import argparse
import csv
import os
import subprocess
import sys
import time

import yaml

from nds_tpu.nds_h.transcode import get_load_time, get_rngseed
from nds_tpu.utils.timelog import TimeLog


def _run(cmd: list[str], backend: str | None = None) -> None:
    from nds_tpu.utils.power_core import subprocess_env
    print("+", " ".join(cmd))
    subprocess.run(cmd, check=True, env=subprocess_env(backend))


def get_power_time(time_log_path: str) -> float:
    """Power Test Time seconds from a power-run CSV log."""
    for _app, query, ms in TimeLog.read(time_log_path):
        if query == "Power Test Time":
            return ms / 1000.0
    raise ValueError(f"no Power Test Time row in {time_log_path}")


def get_perf_metric(scale: float, num_streams: int, tld: float, tpt: float,
                    ttt: float) -> int:
    """3-term NDS-H composite (reference 4-term form:
    `nds/nds_bench.py:334-357`; maintenance term absent in NDS-H)."""
    sq = max(num_streams, 1)
    tld_h = sq * 22 * tld / 3600.0
    tpt_h = sq * 22 * tpt / 3600.0
    ttt_h = ttt / 3600.0
    denom = (tpt_h * ttt_h * tld_h) ** (1.0 / 3.0)
    return int(scale * sq * 22 / denom) if denom > 0 else 0


def run_full_bench(cfg: dict) -> dict:
    paths = cfg["paths"]
    scale = float(cfg.get("scale_factor", 1))
    parallel = int(cfg.get("parallel", 2))
    num_streams = int(cfg.get("num_streams", 2))
    backend = cfg.get("backend", "tpu")
    raw_dir = paths["raw_data"]
    wh_dir = paths["warehouse"]
    stream_dir = paths["streams"]
    report_dir = paths.get("reports", "bench_reports")
    os.makedirs(report_dir, exist_ok=True)
    load_report = os.path.join(report_dir, "load_report.txt")
    metrics = {}

    # YAML ``cache: {dir, readonly}`` (README "Plan cache"): one
    # persistent AOT plan cache shared by every phase subprocess
    from nds_tpu import cache as plan_cache
    plan_cache.export_env(cfg.get("cache"))

    if not cfg.get("skip", {}).get("data_gen", False):
        _run([sys.executable, "-m", "nds_tpu.nds_h.gen_data",
              str(scale), str(parallel), raw_dir, "--overwrite_output"],
             backend="cpu")
    if not cfg.get("skip", {}).get("load_test", False):
        _run([sys.executable, "-m", "nds_tpu.nds_h.transcode",
              raw_dir, wh_dir, load_report], backend="cpu")
    metrics["load_time_s"] = tld = get_load_time(load_report)
    rngseed = get_rngseed(load_report)

    if not cfg.get("skip", {}).get("stream_gen", False):
        from nds_tpu.nds_h.streams import generate_query_streams
        generate_query_streams(stream_dir, num_streams + 1,
                               rng_seed=rngseed, qualification=False)

    power_log = os.path.join(report_dir, "power_time.csv")
    if not cfg.get("skip", {}).get("power_test", False):
        _run([sys.executable, "-m", "nds_tpu.nds_h.power",
              wh_dir, os.path.join(stream_dir, "stream_0.sql"), power_log,
              "--backend", backend,
              "--json_summary_folder", os.path.join(report_dir, "json")],
             backend=backend)
    metrics["power_time_s"] = tpt = get_power_time(power_log)

    tstreams = [os.path.join(stream_dir, f"stream_{i}.sql")
                for i in range(1, num_streams + 1)]
    ttt = None
    if not cfg.get("skip", {}).get("throughput_test", False):
        from nds_tpu.nds_h.throughput import run_streams
        ttt, codes = run_streams(
            wh_dir, tstreams, os.path.join(report_dir, "throughput"),
            backend=backend)
        if any(codes):
            raise SystemExit(f"throughput streams failed: {codes}")
    metrics["throughput_time_s"] = ttt

    # no composite without a real throughput term (a fabricated Ttt would
    # silently skew the geometric mean)
    metrics["metric"] = (get_perf_metric(scale, num_streams, tld, tpt, ttt)
                         if ttt is not None else None)
    out_csv = paths.get("metrics_csv", os.path.join(report_dir,
                                                    "metrics.csv"))
    with open(out_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["scale", "streams", "load_s", "power_s",
                    "throughput_s", "metric", "timestamp"])
        w.writerow([scale, num_streams, tld, tpt, ttt, metrics["metric"],
                    int(time.time())])
    print(f"perf metric: {metrics['metric']} (details in {out_csv})")
    return metrics


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="full NDS-H benchmark")
    p.add_argument("config", help="bench YAML (like nds/bench.yml)")
    args = p.parse_args(argv)
    with open(args.config) as f:
        cfg = yaml.safe_load(f)
    run_full_bench(cfg)


if __name__ == "__main__":
    main()
