select
    sum(l_extendedprice * l_discount) as revenue
from
    lineitem
where
    l_shipdate >= date '{date}'
    and l_shipdate < date '{date}' + interval '1' year
    and l_discount between {discount} - 0.01 and {discount} + 0.01
    and l_quantity < {quantity};
