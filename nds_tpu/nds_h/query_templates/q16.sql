select
    p_brand,
    p_type,
    p_size,
    count(distinct ps_suppkey) as supplier_cnt
from
    partsupp,
    part
where
    p_partkey = ps_partkey
    and p_brand <> '{brand}'
    and p_type not like '{type}%'
    and p_size in ({sizes})
    and ps_suppkey not in (
        select
            s_suppkey
        from
            supplier
        where
            s_comment like '%Customer%Complaints%'
    )
group by
    p_brand,
    p_type,
    p_size
order by
    supplier_cnt desc,
    p_brand,
    p_type,
    p_size;
