select
    s_name,
    s_address
from
    supplier,
    nation
where
    s_suppkey in (
        select
            ps_suppkey
        from
            partsupp
        where
            ps_partkey in (
                select
                    p_partkey
                from
                    part
                where
                    p_name like '{color}%'
            )
            and ps_availqty > (
                select
                    0.5 * sum(l_quantity)
                from
                    lineitem
                where
                    l_partkey = ps_partkey
                    and l_suppkey = ps_suppkey
                    and l_shipdate >= date '{date}'
                    and l_shipdate < date '{date}' + interval '1' year
            )
    )
    and s_nationkey = n_nationkey
    and n_name = '{nation}'
order by
    s_name;
