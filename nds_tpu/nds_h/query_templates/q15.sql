create view revenue{stream} (supplier_no, total_revenue) as
    select
        l_suppkey,
        sum(l_extendedprice * (1 - l_discount))
    from
        lineitem
    where
        l_shipdate >= date '{date}'
        and l_shipdate < date '{date}' + interval '3' month
    group by
        l_suppkey;

select
    s_suppkey,
    s_name,
    s_address,
    s_phone,
    total_revenue
from
    supplier,
    revenue{stream}
where
    s_suppkey = supplier_no
    and total_revenue = (
        select
            max(total_revenue)
        from
            revenue{stream}
    )
order by
    s_suppkey;

drop view revenue{stream};
