"""Newline-delimited-JSON asyncio front for the query server.

Protocol (one JSON object per line, UTF-8):

  request:  {"tenant": "t0", "suite": "nds_h", "sql": "select ...",
             "qname": "query5#3"}
  response: {"status": "ok"|"shed"|"error", "qname", "tenant",
             "elapsed_ms", "rows", "digest", "error"?, "shed_reason"?}

The coroutines here never touch the engine: ``QueryServer.submit``
enqueues onto the engine thread and returns a concurrent Future the
handler awaits via ``asyncio.wrap_future`` — no blocking calls inside
the event loop (ndslint NDS115 enforces that for this package).  One
malformed line answers with a status "error" object instead of killing
the connection; EOF closes it.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json

from nds_tpu.serve.server import QueryServer, Response


def _encode(resp: Response) -> bytes:
    doc = {k: v for k, v in dataclasses.asdict(resp).items()
           if v is not None}
    return (json.dumps(doc) + "\n").encode()


async def handle_connection(server: QueryServer,
                            reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
    try:
        while True:
            line = await reader.readline()
            if not line:
                break
            try:
                doc = json.loads(line)
                fut = server.submit(str(doc.get("tenant", "anon")),
                                    str(doc.get("suite", "nds_h")),
                                    str(doc["sql"]),
                                    str(doc.get("qname", "")))
            except Exception as exc:  # noqa: BLE001 - bad line answers
                writer.write(_encode(Response(
                    "error", error=f"bad request: {exc}")))
                await writer.drain()
                continue
            resp = await asyncio.wrap_future(fut)
            writer.write(_encode(resp))
            await writer.drain()
    finally:
        writer.close()


async def start_tcp(server: QueryServer, host: str = "127.0.0.1",
                    port: int = 0) -> asyncio.AbstractServer:
    """Bind and return the asyncio server (``port=0`` picks a free
    port; read it from ``srv.sockets[0].getsockname()``)."""

    async def _handler(reader, writer):
        await handle_connection(server, reader, writer)

    return await asyncio.start_server(_handler, host, port)


async def request_many(host: str, port: int, docs: list,
                       concurrency: int = 8) -> list:
    """Client helper (tools/ndsload.py): fire ``docs`` with up to
    ``concurrency`` connections, one in-flight request per connection,
    preserving per-doc response pairing. Returns response dicts in
    ``docs`` order."""
    out: list = [None] * len(docs)
    idx = iter(range(len(docs)))

    async def worker():
        reader, writer = await asyncio.open_connection(host, port)
        try:
            for i in idx:
                try:
                    writer.write((json.dumps(docs[i]) + "\n").encode())
                    await writer.drain()
                    line = await reader.readline()
                except Exception as exc:  # noqa: BLE001 - per-doc
                    out[i] = {"status": "error",
                              "error": f"{type(exc).__name__}: {exc}"}
                    break
                if not line:
                    out[i] = {"status": "error",
                              "error": "connection closed"}
                    break
                out[i] = json.loads(line)
        finally:
            writer.close()

    # a worker dying early (connect refused, mid-stream close) must
    # not discard its siblings' responses (return_exceptions swallows
    # the raise; the per-doc errors were recorded where known) or
    # leave None holes the callers' summarizers would crash on
    await asyncio.gather(
        *[worker() for _ in range(max(1, min(concurrency,
                                             len(docs))))],
        return_exceptions=True)
    for i, r in enumerate(out):
        if r is None:
            out[i] = {"status": "error", "error": "no response "
                      "(connection lost before dispatch)"}
    return out
