"""Newline-delimited-JSON asyncio front for the query server.

Protocol (one JSON object per line, UTF-8):

  request:  {"tenant": "t0", "suite": "nds_h", "sql": "select ...",
             "qname": "query5#3", "id": "r-17"}
  response: {"status": "ok"|"shed"|"error", "qname", "tenant",
             "elapsed_ms", "rows", "digest", "error"?, "shed_reason"?,
             "id"?}
  control:  {"op": "ping", "id"?} ->
            {"op": "ping", "status": "ok", "engine_alive": true,
             "queue_depth": N, "inflight": N, "completed": N,
             "replica"?, "id"?}

The ``id`` field is the fleet router's redelivery handle: a response
echoes its request's ``id`` verbatim, and requests carrying ids are
PIPELINED — the handler submits every parsed line immediately and
writes each response as its future resolves, so many requests ride one
connection concurrently (responses may reorder across ids; requests
without ids keep strict one-in-flight FIFO semantics on the client
side, which is what ``request_many`` does). ``op: ping`` is the
app-level health probe: answered from the handler with the engine
thread's liveness, never queued behind traffic, so a router can
distinguish "engine wedged" from "engine busy".

Hostile/stalled clients cannot pin resources: each connection has a
read deadline (``serve.net.read_timeout_s``) after which the reader
coroutine sheds with an explicit status and closes (counted in
``server_conn_timeouts_total``), and a max line length
(``serve.net.max_line_bytes``, enforced via the StreamReader limit) so
an endless unterminated line can never buffer unbounded bytes
(``server_conn_overruns_total``; the connection closes — a mid-line
stream cannot be resynced safely). In-flight responses still deliver
before the close. Every cross-process await here sits under an
``asyncio.wait_for`` deadline (ndslint NDS118 enforces that for this
package): the front must never be able to hang on one dead peer.

The coroutines never touch the engine: ``QueryServer.submit`` enqueues
onto the engine thread and returns a concurrent Future the handler
awaits via ``asyncio.wrap_future`` — no blocking calls inside the
event loop (ndslint NDS115).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json

from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.serve.server import ERROR, SHED, QueryServer, Response

DEFAULT_READ_TIMEOUT_S = 300.0
DEFAULT_MAX_LINE_BYTES = 1 << 20
# bounded write/drain: a peer that stops reading must not pin a writer
WRITE_TIMEOUT_S = 60.0
# how long a closing connection waits for already-admitted requests'
# responses to deliver before dropping them
CLOSE_LINGER_S = 600.0


def net_limits(config=None) -> tuple[float, int]:
    """(read_timeout_s, max_line_bytes) from ``serve.net.*`` config
    keys (0/negative read timeout = no deadline)."""
    timeout, max_line = DEFAULT_READ_TIMEOUT_S, DEFAULT_MAX_LINE_BYTES
    if config is not None:
        try:
            timeout = float(config.get("serve.net.read_timeout_s",
                                       timeout))
        except (TypeError, ValueError):
            pass
        try:
            max_line = int(config.get("serve.net.max_line_bytes",
                                      max_line))
        except (TypeError, ValueError):
            pass
    return timeout, max(1024, max_line)


def _doc_bytes(doc: dict) -> bytes:
    return (json.dumps(doc) + "\n").encode()


def _encode(resp: Response, rid=None) -> bytes:
    doc = {k: v for k, v in dataclasses.asdict(resp).items()
           if v is not None}
    if rid is not None:
        doc["id"] = rid
    return _doc_bytes(doc)


async def handle_connection(server: QueryServer,
                            reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
    read_timeout, _ = net_limits(getattr(server, "config", None))
    wlock = asyncio.Lock()
    tasks: set = set()

    async def _write(payload: bytes) -> None:
        async with wlock:
            writer.write(payload)
            await asyncio.wait_for(writer.drain(),
                                   timeout=WRITE_TIMEOUT_S)

    async def _answer(fut, rid) -> None:
        resp = await asyncio.wrap_future(fut)
        try:
            await _write(_encode(resp, rid))
        except (OSError, asyncio.TimeoutError):
            # connection died while answering: the requester is gone;
            # the fleet router's journal/redelivery is the recovery
            obs_metrics.counter("server_conn_lost_responses_total").inc()

    try:
        while True:
            try:
                if read_timeout > 0:
                    line = await asyncio.wait_for(
                        reader.readline(), timeout=read_timeout)
                else:
                    line = await asyncio.wait_for(
                        reader.readline(), timeout=CLOSE_LINGER_S)
            except asyncio.TimeoutError:
                # stalled client: shed the CONNECTION with an explicit
                # status — a silent close would look like a crash
                obs_metrics.counter("server_conn_timeouts_total").inc()
                try:
                    await _write(_doc_bytes(
                        {"status": SHED,
                         "shed_reason": f"conn-read-timeout:"
                                        f"{read_timeout:g}s"}))
                except (OSError, asyncio.TimeoutError):
                    pass
                break
            except ValueError:
                # line exceeded the StreamReader limit (max_line_bytes
                # set in start_tcp): the stream is mid-line and cannot
                # be resynced — answer and close
                obs_metrics.counter("server_conn_overruns_total").inc()
                try:
                    await _write(_doc_bytes(
                        {"status": SHED,
                         "shed_reason": "line-too-long"}))
                except (OSError, asyncio.TimeoutError):
                    pass
                break
            if not line:
                break
            try:
                doc = json.loads(line)
            except ValueError as exc:
                await _write(_doc_bytes(
                    {"status": ERROR, "error": f"bad request: {exc}"}))
                continue
            rid = doc.get("id")
            if isinstance(doc, dict) and doc.get("op") == "ping":
                pong = {"op": "ping", "status": "ok"}
                ping = getattr(server, "ping", None)
                if callable(ping):
                    pong.update(ping())
                if rid is not None:
                    pong["id"] = rid
                await _write(_doc_bytes(pong))
                continue
            try:
                fut = server.submit(str(doc.get("tenant", "anon")),
                                    str(doc.get("suite", "nds_h")),
                                    str(doc["sql"]),
                                    str(doc.get("qname", "")))
            except Exception as exc:  # noqa: BLE001 - bad line answers
                await _write(_doc_bytes(
                    {"status": ERROR, "error": f"bad request: {exc}",
                     **({"id": rid} if rid is not None else {})}))
                continue
            # pipelined: submit now, answer when the engine resolves —
            # the queue (not the connection) is where requests wait
            t = asyncio.ensure_future(_answer(fut, rid))
            tasks.add(t)
            t.add_done_callback(tasks.discard)
    finally:
        if tasks:
            # admitted requests still get their answers before the
            # close (bounded: the engine's shed-not-crash contract
            # resolves every future, but a wedged engine must not pin
            # this coroutine forever)
            try:
                await asyncio.wait_for(
                    asyncio.gather(*list(tasks), return_exceptions=True),
                    timeout=CLOSE_LINGER_S)
            except asyncio.TimeoutError:
                for t in list(tasks):
                    t.cancel()
        writer.close()


async def start_tcp(server: QueryServer, host: str = "127.0.0.1",
                    port: int = 0) -> asyncio.AbstractServer:
    """Bind and return the asyncio server (``port=0`` picks a free
    port; read it from ``srv.sockets[0].getsockname()``). The
    StreamReader limit is ``serve.net.max_line_bytes``."""
    _, max_line = net_limits(getattr(server, "config", None))

    async def _handler(reader, writer):
        await handle_connection(server, reader, writer)

    return await asyncio.start_server(_handler, host, port,
                                      limit=max_line)


async def request_many(host: str, port: int, docs: list,
                       concurrency: int = 8,
                       timeout_s: float = 600.0) -> list:
    """Client helper (tools/ndsload.py): fire ``docs`` with up to
    ``concurrency`` connections, one in-flight request per connection,
    preserving per-doc response pairing. Returns response dicts in
    ``docs`` order."""
    out: list = [None] * len(docs)
    idx = iter(range(len(docs)))

    async def worker():
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout=timeout_s)
        try:
            for i in idx:
                try:
                    writer.write((json.dumps(docs[i]) + "\n").encode())
                    await asyncio.wait_for(writer.drain(),
                                           timeout=timeout_s)
                    line = await asyncio.wait_for(reader.readline(),
                                                  timeout=timeout_s)
                except Exception as exc:  # noqa: BLE001 - per-doc
                    out[i] = {"status": "error",
                              "error": f"{type(exc).__name__}: {exc}"}
                    break
                if not line:
                    out[i] = {"status": "error",
                              "error": "connection closed"}
                    break
                out[i] = json.loads(line)
        finally:
            writer.close()

    # a worker dying early (connect refused, mid-stream close) must
    # not discard its siblings' responses (return_exceptions swallows
    # the raise; the per-doc errors were recorded where known) or
    # leave None holes the callers' summarizers would crash on
    await asyncio.gather(
        *[worker() for _ in range(max(1, min(concurrency,
                                             len(docs))))],
        return_exceptions=True)
    for i, r in enumerate(out):
        if r is None:
            out[i] = {"status": "error", "error": "no response "
                      "(connection lost before dispatch)"}
    return out
