"""Async query serving layer (README "Serving", "Serve fleet").

Wraps the unified ExecutionPipeline + parameterized-plan machinery in a
persistent session server: concurrent NDS + NDS-H requests against one
shared warehouse, admission control fed by the MemoryGovernor's
pre-dispatch projections, queue-depth/deadline brownout (shed, never
collapse), per-tenant metrics on the snapshot/OpenMetrics emitter, and
per-request BenchReport-compatible summaries `ndsreport analyze` can
read. ``server.QueryServer`` is the in-process core; ``net`` adds the
newline-delimited-JSON asyncio TCP front; ``replica`` wraps one server
in the supervised-fleet contract (announce/heartbeat/drain-to-75);
``fleet.FleetRouter`` routes by plan digest across N replicas with
health gating and journaled zero-loss failover."""

from nds_tpu.serve.fleet import (  # noqa: F401
    FleetRouter, ReplicaClient, RequestJournal, launch_fleet,
    scale_out,
)
from nds_tpu.serve.server import QueryServer, Request, Response  # noqa: F401
