"""Async query serving layer (README "Serving").

Wraps the unified ExecutionPipeline + parameterized-plan machinery in a
persistent session server: concurrent NDS + NDS-H requests against one
shared warehouse, admission control fed by the MemoryGovernor's
pre-dispatch projections, queue-depth/deadline brownout (shed, never
collapse), per-tenant metrics on the snapshot/OpenMetrics emitter, and
per-request BenchReport-compatible summaries `ndsreport analyze` can
read. ``server.QueryServer`` is the in-process core; ``net`` adds the
newline-delimited-JSON asyncio TCP front."""

from nds_tpu.serve.server import QueryServer, Request, Response  # noqa: F401
