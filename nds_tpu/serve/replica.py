"""One engine replica of the serve fleet (launched by
resilience/supervise.ReplicaSupervisor, routed by serve/fleet.py).

    python -m nds_tpu.serve.replica --name r0 \
        --announce /fleet/announce/r0.json \
        --gen_scale 0.01 --gen_nds_tables store_sales,date_dim,... \
        --backend tpu --cache_dir /fleet/plancache \
        --summary_dir /fleet/serve_json

Wraps PR 11's QueryServer in the fleet contract:

- **Warehouse** either loaded from disk (``--nds_h_data``/``--nds_data``
  like ``python -m nds_tpu.serve``) or regenerated in-process from the
  seeded datagen (``--gen_scale``): datagen streams derive from
  ``(seed, table, step)``, so every replica — and the router's oracle —
  materializes bit-identical tables without sharing files.
- **Announce** — binds TCP on ``--port`` (0 = free port) and publishes
  ``{replica, host, port, pid, incarnation}`` atomically to
  ``--announce``; a resumed incarnation overwrites it with its NEW
  port, which is how the router discovers the comeback.
- **Liveness** — arms the metrics snapshotter and watchdog from the
  supervisor's env (``NDS_TPU_METRICS_SNAP`` / ``NDS_TPU_WATCHDOG``)
  and beats ``serve`` only while the engine thread is alive, so a
  wedged engine reads as a stall (exit 86) while an idle-but-healthy
  replica does not.
- **Drain** — SIGTERM runs ``begin_drain()`` (new submits shed
  ``server-stopping`` — departure notices the router redelivers),
  waits for in-flight work to reach zero under ``engine.drain_s``
  (the boundary-pipelined overlapped request resolves here too: its
  future is in-flight until ``_finalize_prev`` answers it), then exits
  :data:`~nds_tpu.resilience.drain.EXIT_RESUMABLE` (75). The
  supervisor relaunches warm — 0 compiles by construction, the shared
  ``cache.dir`` AOT store was paid by the first owner of each plan.
  SIGINT drains the same way but exits 0 (operator stop, not resume).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

from nds_tpu.resilience.drain import EXIT_RESUMABLE


def parse_incarnation(stream_name: "str | None") -> int:
    """``r0#r2`` -> 2 (the supervisor's incarnation suffix); bare
    names are incarnation 0."""
    if stream_name and "#r" in stream_name:
        try:
            return int(stream_name.rsplit("#r", 1)[1])
        except ValueError:
            return 0
    return 0


def _gen_tables(server, scale: float, nds_tables: "list[str]",
                h_tables: "list[str] | None" = None) -> int:
    """Seeded in-process warehouse: every replica (and the router's
    oracle) generates identical arrays from the deterministic datagen
    streams — fleet digest parity needs no shared storage."""
    from nds_tpu.datagen import tpcds as gen_d
    from nds_tpu.datagen import tpch as gen_h
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds.schema import get_schemas as d_schemas
    from nds_tpu.nds_h.schema import get_schemas as h_schemas
    n = 0
    hs = h_schemas()
    for t in (h_tables if h_tables is not None else list(hs)):
        server.register_table(
            from_arrays(t, hs[t], gen_h.gen_table(t, scale)), "nds_h")
        n += 1
    ds = d_schemas()
    for t in nds_tables:
        server.register_table(
            from_arrays(t, ds[t], gen_d.gen_table(t, scale)), "nds")
        n += 1
    return n


def build_server(args):
    """QueryServer from replica CLI args (importable so tests build
    the same server in-process)."""
    from nds_tpu.serve import QueryServer
    from nds_tpu.utils.config import EngineConfig
    overrides = {"engine.backend": args.backend,
                 "serve.replica_id": args.name}
    if args.cache_dir:
        overrides["cache.dir"] = args.cache_dir
    if args.summary_dir:
        overrides["serve.summary_dir"] = args.summary_dir
    if args.max_queue is not None:
        overrides["serve.max_queue"] = str(args.max_queue)
    if args.deadline_ms is not None:
        overrides["serve.deadline_ms"] = str(args.deadline_ms)
    for kv in args.property or []:
        k, _, v = kv.partition("=")
        overrides[k.strip()] = v.strip()
    cfg = EngineConfig(args.template, args.property_file, overrides)
    srv = QueryServer(cfg)
    if args.gen_scale is not None:
        nds_tables = [t for t in
                      (args.gen_nds_tables or "").split(",") if t]
        h_tables = ([t for t in args.gen_nds_h_tables.split(",") if t]
                    if args.gen_nds_h_tables is not None else None)
        _gen_tables(srv, args.gen_scale, nds_tables, h_tables)
    from nds_tpu.serve.__main__ import _load_suite
    for suite, d in (("nds_h", args.nds_h_data), ("nds", args.nds_data)):
        if d:
            _load_suite(srv, suite, d, args.input_format)
    return srv, cfg


async def serve_replica(srv, host: str, port: int,
                        announce_path: "str | None",
                        drain_s: float) -> int:
    """Serve until signalled; returns the process exit code (75 on a
    SIGTERM drain, 0 on SIGINT)."""
    import signal

    from nds_tpu.io.integrity import write_json_atomic
    from nds_tpu.resilience import watchdog
    from nds_tpu.serve.net import start_tcp

    tcp = await start_tcp(srv, host, port)
    bound = tcp.sockets[0].getsockname()[1]
    inc = parse_incarnation(os.environ.get(watchdog.STREAM_ENV))
    if announce_path:
        write_json_atomic(announce_path, {
            "replica": srv.replica_id, "host": host, "port": bound,
            "pid": os.getpid(), "incarnation": inc,
            "ts": time.time()})
    print(f"[replica {srv.replica_id}] inc={inc} listening on "
          f"{host}:{bound}", flush=True)

    drain_sig: "dict[str, int | None]" = {"sig": None}
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()

    def _on_signal(sig):
        drain_sig["sig"] = sig
        stop.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        # loop-native handlers: the default KeyboardInterrupt path can
        # land mid-callback and skip the drain below
        loop.add_signal_handler(sig, _on_signal, sig)

    async def _beat_loop():
        # IDLE-only heartbeat: the watchdog alarms on the NEWEST beat
        # across all units, so beating while requests are in flight
        # would mask a wedged query (the executors beat per chunk
        # while real work progresses — that is the busy-path
        # liveness). An idle replica beats here so quiet is not
        # mistaken for a stall; a dead engine thread stops both
        # sources and the watchdog (then the supervisor backstop)
        # fires.
        while not stop.is_set():
            if srv._thread is not None and srv._thread.is_alive():
                with srv._lock:
                    inflight = srv._inflight
                if inflight == 0:
                    watchdog.beat("serve", phase="idle")
            # completed-count into the snapshot progress dict (the
            # supervisor's liveness/resume bookkeeping reads it)
            getattr(srv, "_progress_tick", lambda: None)()
            await asyncio.sleep(0.25)

    beater = asyncio.ensure_future(_beat_loop())
    await stop.wait()

    # drain: refuse new work, finish what's in flight (including a
    # boundary-overlapped request — it stays in-flight until its
    # handle resolves), then exit resumable
    print(f"[replica {srv.replica_id}] draining "
          f"(budget {drain_s:g}s)", flush=True)
    tcp.close()     # the listener only: live connections keep
    await asyncio.wait_for(  # serving while the backlog drains
        tcp.wait_closed(), timeout=30.0)
    srv.begin_drain()
    deadline = time.monotonic() + max(0.1, drain_s)
    while time.monotonic() < deadline:
        with srv._lock:
            inflight = srv._inflight
        if inflight == 0:
            break
        await asyncio.sleep(0.02)
    # settle: let connection handlers flush resolved responses to
    # their sockets before the process exits
    await asyncio.sleep(0.1)
    beater.cancel()
    rc = (EXIT_RESUMABLE
          if drain_sig["sig"] == signal.SIGTERM else 0)
    print(f"[replica {srv.replica_id}] drained: {srv.stats} "
          f"-> exit {rc}", flush=True)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--name", required=True,
                    help="replica id (stamped on responses/summaries)")
    ap.add_argument("--announce",
                    help="atomic JSON endpoint file the router watches")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 picks a free port (published via announce)")
    ap.add_argument("--nds_h_data", help="NDS-H warehouse dir")
    ap.add_argument("--nds_data", help="NDS warehouse dir")
    ap.add_argument("--input_format", default="parquet")
    ap.add_argument("--gen_scale", type=float, default=None,
                    help="regenerate the warehouse in-process from the "
                         "seeded datagen at this scale factor")
    ap.add_argument("--gen_nds_tables", default="",
                    help="comma list of NDS tables to generate")
    ap.add_argument("--gen_nds_h_tables", default=None,
                    help="comma list of NDS-H tables (default: all)")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--cache_dir",
                    help="SHARED persistent AOT plan cache (cache.dir) "
                         "— warm restarts and late joiners compile 0")
    ap.add_argument("--summary_dir")
    ap.add_argument("--max_queue", type=int, default=None)
    ap.add_argument("--deadline_ms", type=int, default=None)
    ap.add_argument("--template", help="engine template file")
    ap.add_argument("--property_file", help="k=v property overrides")
    ap.add_argument("--property", action="append",
                    help="inline k=v override (repeatable)")
    args = ap.parse_args(argv)
    if (args.gen_scale is None and not args.nds_h_data
            and not args.nds_data):
        ap.error("need --gen_scale or --nds_h_data/--nds_data")

    from nds_tpu.obs.snapshot import MetricsSnapshotter
    from nds_tpu.resilience import drain as drain_mod
    from nds_tpu.resilience import watchdog

    srv, cfg = build_server(args)
    progress = {"replica": args.name, "queries_completed": 0}

    def _progress_tick():
        with srv._lock:
            progress["queries_completed"] = srv.stats["completed"]
    # the beat loop inside serve_replica() refreshes this each tick;
    # the snapshotter daemon publishes it at its own interval
    srv._progress_tick = _progress_tick

    snap = MetricsSnapshotter.from_env(progress)
    if snap:
        snap.start()
    run_dir = (args.summary_dir or
               (os.path.dirname(args.announce) if args.announce
                else "."))
    wd = watchdog.Watchdog.from_env(run_dir)
    if wd:
        wd.start()
    srv.start()
    try:
        rc = asyncio.run(serve_replica(
            srv, args.host, args.port, args.announce,
            drain_mod.drain_seconds(cfg)))
    finally:
        _progress_tick()
        srv.stop()
        if snap:
            snap.stop()  # final snapshot always lands
        print(f"[replica {args.name}] stopped: {srv.stats}",
              flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
