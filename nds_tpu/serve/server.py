"""The persistent query server core: one warehouse, many tenants.

Architecture ("Scalable, Fast Cloud Computing with Execution
Templates"): the expensive control-plane work — parse, plan,
parameterize, compile — is cached; the per-request path is admission,
parameter binding, and a pure tensor-program dispatch.  One ENGINE
thread owns every session/executor touch (the engine's executors are
single-threaded by design; jax's async dispatch already overlaps device
work), so concurrency lives in the queue: a request is in flight from
admission to completion, and the engine thread drains same-template
groups back-to-back against one shared compiled program.

Admission / brownout (``serve.*`` config keys, utils/config.py):

- queue depth  >= ``serve.max_queue``      -> shed at submit
- queued age   >  ``serve.deadline_ms``    -> shed at dequeue
- governor projection > budget x ``serve.shed_factor`` -> shed at
  dispatch (the MemoryGovernor's pre-dispatch projection, via
  ``ExecutionPipeline.admission_projection``; inside the factor the
  governor's own demote-don't-die machinery handles pressure)

Every shed increments ``server_shed_total`` (plus the tenant-labeled
variant) and completes the request with status "shed" — load PAST
saturation degrades the answer rate, never the process.  Per-request
summaries are BenchReport-compatible JSONs (``tenant`` field attached)
written to ``serve.summary_dir``, so ``ndsreport analyze`` reports
serving latency like any run dir; per-tenant request counters and
latency histograms publish through the live snapshot/OpenMetrics
emitter (obs/snapshot.py).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field

from nds_tpu.analysis import locksan
from nds_tpu.obs import metrics as obs_metrics

DEFAULT_MAX_QUEUE = 64
DEFAULT_MAX_BATCH = 8
DEFAULT_DEADLINE_MS = 0        # 0 = no queue-age deadline
DEFAULT_SHED_FACTOR = 1.5

SHED = "shed"
OK = "ok"
ERROR = "error"


@dataclass
class Request:
    tenant: str
    suite: str                  # "nds" | "nds_h"
    sql: str
    qname: str = ""
    enqueued: float = field(default_factory=time.monotonic)
    future: Future = field(default_factory=Future)


@dataclass
class Response:
    status: str                 # ok | shed | error
    qname: str = ""
    tenant: str = ""
    elapsed_ms: float = 0.0
    rows: int = 0
    digest: "str | None" = None
    error: "str | None" = None
    shed_reason: "str | None" = None
    replica: "str | None" = None


def _tenant_counter(name: str, tenant: str):
    return obs_metrics.counter(
        obs_metrics.labeled(name, tenant=tenant))


class QueryServer:
    """In-process server core. ``start()`` spins the engine thread;
    ``submit()`` is thread-safe and returns a concurrent Future of
    Response; ``stop()`` drains (queued requests shed) and joins."""

    def __init__(self, config=None, summary_dir: "str | None" = None):
        from nds_tpu.utils.config import EngineConfig
        self.config = config or EngineConfig()
        self.summary_dir = summary_dir or self.config.get(
            "serve.summary_dir")
        self.max_queue = self._cfg_int("serve.max_queue",
                                       DEFAULT_MAX_QUEUE)
        self.max_batch = max(1, self._cfg_int("serve.max_batch",
                                              DEFAULT_MAX_BATCH))
        self.deadline_ms = self._cfg_int("serve.deadline_ms",
                                         DEFAULT_DEADLINE_MS)
        try:
            self.shed_factor = float(self.config.get(
                "serve.shed_factor", DEFAULT_SHED_FACTOR))
        except (TypeError, ValueError):
            self.shed_factor = DEFAULT_SHED_FACTOR
        # deque + condition (not queue.Queue): template batching must
        # EXTRACT matching members in place so non-matching requests
        # keep their arrival position — a tail re-enqueue would let
        # sustained same-template traffic starve an early stranger
        self._queue: "deque[Request]" = deque()
        self._cv = locksan.condition("serve.QueryServer._cv")
        self._running = False
        self._stopped = False
        self._thread: "threading.Thread | None" = None
        self._lock = locksan.lock("serve.QueryServer._lock")
        self._inflight = 0
        self.stats = {"submitted": 0, "completed": 0, "shed": 0,
                      "errors": 0, "batched": 0,
                      "max_inflight": 0}
        # fleet identity: set by serve/replica.py (or the
        # NDS_TPU_REPLICA env the supervisor arms) so every response,
        # summary, and labeled metric names which ring member answered
        self.replica_id = (os.environ.get("NDS_TPU_REPLICA")
                           or str(self.config.get("serve.replica_id",
                                                  "") or "")
                           or None)
        # query-boundary pipelining (engine/pipeline_io.py; README
        # "Pipelined execution"): with engine.prefetch.boundary on the
        # engine thread dispatches request N+1 while request N's
        # compactor output is still in flight D2H — the async handle's
        # result() is the sync point. Off by default.
        from nds_tpu.engine import pipeline_io
        self._boundary = pipeline_io.boundary_enabled(self.config)
        self._build_engine()

    # ------------------------------------------------------- plumbing

    def _cfg_int(self, key: str, default: int) -> int:
        try:
            return self.config.get_int(key, default)
        except (TypeError, ValueError):
            return default

    def _build_engine(self) -> None:
        """One session + ExecutionPipeline per suite. The warehouse is
        shared storage, but the NAMESPACES are per-suite (TPC-H and
        TPC-DS both define ``customer``, with different schemas), so
        each suite keeps its own table registry and its pipeline keeps
        its own executor/buffer/compile state — stable across
        interleaved suite traffic, which one shared pipeline's
        registry-identity check would thrash on."""
        from nds_tpu.engine.scheduler import make_pipeline
        from nds_tpu.engine.session import Session
        from nds_tpu.utils.power_core import prepare_engine
        backend = self.config.get("engine.backend", "cpu")
        prepare_engine(self.config)
        self.pipelines = {
            "nds": make_pipeline(self.config, backend),
            "nds_h": make_pipeline(self.config, backend),
        }
        self.sessions = {
            "nds": Session.for_nds(self.pipelines["nds"],
                                   parameterize=True),
            "nds_h": Session.for_nds_h(self.pipelines["nds_h"],
                                       parameterize=True),
        }

    def register_table(self, table, suite: "str | None" = None) -> None:
        """Load-phase API (NOT thread-safe vs a running server): add
        one warehouse table to ``suite``'s namespace (both namespaces
        when None — for genuinely shared tables)."""
        targets = ([self.sessions[suite]] if suite
                   else list(self.sessions.values()))
        for s in targets:
            s.register_table(table)

    # ------------------------------------------------------ lifecycle

    def start(self) -> "QueryServer":
        if self._thread is None:
            with self._cv:
                # restartable: a stopped server that start()s again
                # must serve, not zombie-shed behind a stale flag
                self._stopped = False
                self._running = True
            self._thread = threading.Thread(
                target=self._engine_loop, name="nds-tpu-serve-engine",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._cv:
            # under the same condition submit() enqueues with: after
            # this, no request can slip onto the queue past the drain
            # below
            self._running = False
            self._stopped = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None
        # anything still queued sheds: stop() must never strand a
        # caller on an unfulfilled future
        while True:
            with self._cv:
                if not self._queue:
                    break
                req = self._queue.popleft()
            self._finish_shed(req, "server-stopping")

    def begin_drain(self) -> None:
        """Stop ADMITTING without stopping SERVING: new submits shed
        ``server-stopping`` (the fleet router redelivers those — they
        are departure notices, not answers) while the engine thread
        keeps draining the backlog. The drain sequence is
        ``begin_drain()`` → wait for in-flight to reach zero (bounded
        by ``engine.drain_s``) → ``stop()``; serve/replica.py runs it
        on SIGTERM before exiting 75 (resumable)."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    def ping(self) -> dict:
        """App-level health probe payload (``op: ping`` on the TCP
        front; never routed through the request queue, so a saturated
        queue reads as BUSY — deep queue, live engine — while a wedged
        or dead engine thread reads as UNHEALTHY)."""
        alive = self._thread is not None and self._thread.is_alive()
        with self._cv:
            depth = len(self._queue)
            draining = self._stopped and self._running
        with self._lock:
            inflight = self._inflight
            completed = self.stats["completed"]
        doc = {"engine_alive": alive, "queue_depth": depth,
               "inflight": inflight, "completed": completed}
        if draining:
            doc["draining"] = True
        if self.replica_id:
            doc["replica"] = self.replica_id
        return doc

    # ------------------------------------------------------ admission

    def submit(self, tenant: str, suite: str, sql: str,
               qname: str = "") -> "Future[Response]":
        """Thread-safe request intake with queue-depth brownout."""
        with self._lock:
            # default qname minted under the lock: concurrent submits
            # must never share one (summary filenames key on it)
            req = Request(tenant=tenant, suite=suite, sql=sql,
                          qname=qname
                          or f"q{self.stats['submitted']}")
            self.stats["submitted"] += 1
            self._inflight += 1
            self.stats["max_inflight"] = max(self.stats["max_inflight"],
                                             self._inflight)
        obs_metrics.counter("server_requests_total").inc()
        _tenant_counter("server_requests_total", tenant).inc()
        if suite not in self.sessions:
            self._finish_error(req, f"unknown suite {suite!r}")
            return req.future
        with self._cv:
            # the stopped check and the append share stop()'s
            # condition: a stop() racing this submit either sees the
            # request on the queue (and sheds it in its drain) or we
            # see _stopped here — the future resolves either way,
            # never strands
            if self._stopped:
                # a not-yet-started server still queues; start() will
                # serve the backlog
                shed = "server-stopping"
            elif len(self._queue) >= self.max_queue:
                shed = f"queue-depth:{self.max_queue}"
            else:
                shed = None
                self._queue.append(req)
                self._cv.notify()
            # depth captured under the condition (the engine thread
            # mutates the deque); the gauge write happens outside it
            depth = len(self._queue)
        if shed:
            self._finish_shed(req, shed)
            return req.future
        obs_metrics.gauge("server_queue_depth").set(depth)
        return req.future

    # ------------------------------------------------- engine thread

    def _engine_loop(self) -> None:
        pending: "dict | None" = None
        while True:
            with self._cv:
                while (self._running and not self._queue
                       and pending is None):
                    self._cv.wait(timeout=0.1)
                if not self._running:
                    break
                req = (self._queue.popleft() if self._queue else None)
            if req is None:
                # queue drained: the overlapped request is the only
                # work left — resolve it rather than idle on it
                self._finalize_prev(pending)
                pending = None
                continue
            try:
                pending = self._serve_group(req, pending)
            except Exception as exc:  # noqa: BLE001 - request-scoped
                # an unexpected engine-loop failure bills THIS request
                # and keeps serving (shed-not-crash applies to bugs too)
                self._finish_error(req,
                                   f"{type(exc).__name__}: {exc}")
            with self._cv:
                depth = len(self._queue)
            obs_metrics.gauge("server_queue_depth").set(depth)
        # stop(): never strand an overlapped in-flight request
        self._finalize_prev(pending)

    def _too_old(self, req: Request) -> bool:
        return (self.deadline_ms > 0
                and (time.monotonic() - req.enqueued) * 1000
                > self.deadline_ms)

    def _plan_for(self, req: Request):
        """(planned, plan_digest | None) through the session's bounded
        plan cache; the digest groups same-template in-flight requests
        onto one compiled program."""
        from nds_tpu.sql import params as sqlparams
        s = self.sessions[req.suite]
        key = (req.sql, s._views_signature())
        planned = s._planned_for(key, req.sql)
        if isinstance(planned, tuple):
            return planned, None
        key = sqlparams.plan_key(planned)
        # params.plan_key IS the device executor's compile-cache key:
        # batching on it guarantees the group really shares a program
        return planned, (key[1] if key else None)

    def _finalize_prev(self, pending: "dict | None") -> None:
        """Resolve an overlapped request's result (idempotent — the
        engine loop's catch-all may race a group path that already
        resolved it). A finalize-path failure (summary write on a full
        disk) still answers the request: shed-not-crash applies to the
        bookkeeping too, and a stranded future would hang its client
        forever."""
        if pending is None or pending.get("_finalized"):
            return
        pending["_finalized"] = True
        try:
            self._finalize_one(pending)
        except Exception as exc:  # noqa: BLE001 - request-scoped
            # _resolve is set-once, so if _finalize_one already
            # answered before raising this is a counted no-op
            self._finish_error(pending["req"],
                               f"{type(exc).__name__}: {exc}")

    def _serve_group(self, req: Request,
                     pending: "dict | None" = None) -> "dict | None":
        """Serve one dequeued request, plus every queued request with
        the SAME parameterized plan digest (template batching: the
        group shares one compiled program and drains back-to-back
        without re-entering the scheduler between strangers). With
        boundary pipelining on, a single (unbatched) request dispatches
        BEFORE the previous request's result is taken — its device
        work and D2H overlap this plan+dispatch — and the new pending
        record is returned to the engine loop; ``pending`` resolves at
        the overlap point either way."""
        if self._too_old(req):
            self._finish_shed(req, "deadline")
            self._finalize_prev(pending)
            return None
        try:
            planned, digest = self._plan_for(req)
        except Exception as exc:  # noqa: BLE001 - plan errors answer
            self._finish_error(req, f"{type(exc).__name__}: {exc}")
            self._finalize_prev(pending)
            return None
        group = [req]
        if digest is not None:
            # EXTRACT same-digest peers (bounded) from the queue in
            # place: non-matching requests keep their arrival position
            # (the single engine thread is the only remover, so the
            # snapshot below stays valid while planning outside the
            # condition)
            with self._cv:
                candidates = list(self._queue)
            from nds_tpu.resilience import faults
            taken: list = []
            for peer in candidates:
                if len(group) + len(taken) >= self.max_batch:
                    break
                try:
                    # fault injection suppressed (the warmup
                    # precedent): the scan must not consume a plan
                    # fault scheduled for the peer's own dispatch —
                    # and an unplannable peer stays QUEUED, to be
                    # answered (with retry semantics intact) when it
                    # is dequeued in its own right
                    with faults.suppress():
                        _p, pdig = self._plan_for(peer)
                except Exception:  # noqa: BLE001 - answered at dequeue
                    continue
                if pdig == digest and peer.suite == req.suite \
                        and not self._too_old(peer):
                    taken.append(peer)
            if taken:
                drop = {id(p) for p in taken}
                with self._cv:
                    self._queue = deque(
                        r for r in self._queue if id(r) not in drop)
                group.extend(taken)
            if len(group) > 1:
                # under the stats lock: submit() mutates sibling keys
                # from caller threads while the engine thread runs this
                # (the ndsraces NDSR201 finding that proved the auditor)
                with self._lock:
                    self.stats["batched"] += len(group) - 1
                obs_metrics.counter("server_batched_total").inc(
                    len(group) - 1)
        if self._boundary and len(group) == 1:
            # overlap: dispatch this request first, THEN take the
            # previous one's result while this one runs on device
            pend = self._dispatch_one(req)
            self._finalize_prev(pending)
            return pend
        # batched groups (and the boundary-off path) run sync: the
        # group drains back-to-back against one compiled program, so
        # the previous request resolves first
        self._finalize_prev(pending)
        for member in group:
            try:
                self._serve_one(member)
            except Exception as exc:  # noqa: BLE001 - member-scoped
                # one member's failure must not strand the rest of the
                # group (or double-resolve the leader from the engine
                # loop's catch-all)
                self._finish_error(member,
                                   f"{type(exc).__name__}: {exc}")
        return None

    def _admission_shed_reason(self, suite: str,
                               planned) -> "str | None":
        """Memory-pressure brownout: past ``serve.shed_factor`` x the
        governor budget, rejecting is safer than queueing demoted
        work (inside the factor the governor demotes placements
        instead)."""
        proj = getattr(self.pipelines.get(suite),
                       "admission_projection", None)
        if proj is None:
            return None
        projected, budget = proj(planned)
        if budget > 0 and projected > budget * self.shed_factor:
            return (f"governor:projected:{projected}"
                    f">{self.shed_factor}x budget:{budget}")
        return None

    def _serve_one(self, req: Request) -> None:
        pend = self._dispatch_one(req)
        if pend is not None:
            self._finalize_one(pend)

    def _dispatch_one(self, req: Request) -> "dict | None":
        """Admission + async dispatch of one request. Returns the
        pending record ``_finalize_one`` resolves (possibly after the
        NEXT request dispatched — the boundary overlap), or None when
        the request already answered (shed, plan error)."""
        from nds_tpu.utils.report import BenchReport
        if self._too_old(req):
            self._finish_shed(req, "deadline")
            return None
        s = self.sessions[req.suite]
        try:
            planned, _digest = self._plan_for(req)
        except Exception as exc:  # noqa: BLE001
            self._finish_error(req, f"{type(exc).__name__}: {exc}")
            return None
        if not isinstance(planned, tuple):
            reason = self._admission_shed_reason(req.suite, planned)
            if reason:
                self._finish_shed(req, reason)
                return None
        report = BenchReport(req.qname, {"tenant": req.tenant,
                                         "suite": req.suite})
        report.begin_async()
        pend = {"req": req, "report": report,
                "t0": time.monotonic()}
        try:
            # focus: an overlapped predecessor's collector is still
            # registered — this dispatch's anomalies are THIS request's
            with report.focus_failures():
                pend["handle"] = s.sql_async(req.sql)
        except Exception as exc:  # noqa: BLE001 - billed at finalize
            pend["dispatch_error"] = exc
        return pend

    def _finalize_one(self, pend: dict) -> None:
        """Blocking half of one dispatched request: the async handle's
        result() is the sync point; everything downstream (summary,
        digest, tenant metrics, future resolution) is unchanged from
        the serial path."""
        from nds_tpu.io.result_io import result_digest
        req, report = pend["req"], pend["report"]
        s = self.sessions[req.suite]
        hold: dict = {}
        err = pend.pop("dispatch_error", None)
        if err is None:
            try:
                with report.focus_failures():
                    hold["result"] = pend["handle"].result()
            except Exception as exc:  # noqa: BLE001 - billed below
                err = exc
        summary = report.end_async(error=err)
        elapsed_ms = (time.monotonic() - pend["t0"]) * 1000
        report.attach_tenant(req.tenant)
        report.attach_replica(self.replica_id)
        from nds_tpu.resilience.retry import RetryStats
        ex = s._executor_factory(s.tables)
        report.attach_retry(getattr(ex, "last_stats", None)
                            or RetryStats())
        report.attach_schedule(getattr(ex, "last_schedule", None))
        digest = result_digest(hold.get("result"))
        report.attach_result_digest(digest)
        failed = not report.is_success()
        obs_metrics.histogram("server_request_seconds").observe(
            elapsed_ms / 1000.0)
        obs_metrics.histogram(obs_metrics.labeled(
            "server_request_seconds", tenant=req.tenant)).observe(
            elapsed_ms / 1000.0)
        if self.summary_dir:
            os.makedirs(self.summary_dir, exist_ok=True)
            report.write_summary(prefix="serve",
                                 out_dir=self.summary_dir)
        if failed:
            exc = (summary.get("exceptions") or ["unknown"])[-1]
            self._finish_error(req, str(exc))
            return
        res = hold.get("result")
        if not self._resolve(req, Response(
                OK, qname=req.qname, tenant=req.tenant,
                elapsed_ms=round(elapsed_ms, 3),
                rows=getattr(res, "nrows", 0), digest=digest,
                replica=self.replica_id)):
            return
        with self._lock:
            self.stats["completed"] += 1
            self._inflight -= 1
        obs_metrics.counter("server_completed_total").inc()
        _tenant_counter("server_completed_total", req.tenant).inc()

    # ------------------------------------------------------- outcomes

    @staticmethod
    def _resolve(req: Request, resp: Response) -> bool:
        """Resolve a request's future exactly once; a second
        resolution attempt (engine-loop catch-all racing a member
        outcome) is a counted no-op, never an InvalidStateError that
        would kill the engine thread."""
        try:
            req.future.set_result(resp)
            return True
        except Exception:  # noqa: BLE001 - already resolved/cancelled
            obs_metrics.counter("server_double_resolve_total").inc()
            return False

    def _finish_shed(self, req: Request, reason: str) -> None:
        if not self._resolve(req, Response(
                SHED, qname=req.qname, tenant=req.tenant,
                shed_reason=reason, replica=self.replica_id)):
            return
        with self._lock:
            self.stats["shed"] += 1
            self._inflight -= 1
        obs_metrics.counter("server_shed_total").inc()
        _tenant_counter("server_shed_total", req.tenant).inc()

    def _finish_error(self, req: Request, error: str) -> None:
        if not self._resolve(req, Response(
                ERROR, qname=req.qname, tenant=req.tenant,
                error=error, replica=self.replica_id)):
            return
        with self._lock:
            self.stats["errors"] += 1
            self._inflight -= 1
        obs_metrics.counter("server_errors_total").inc()
