"""Replicated serve fleet: health-gated routing with zero-loss failover.

Topology (README "Serve fleet"): N ``serve/replica.py`` processes —
each PR 11's QueryServer engine loop behind the JSON-lines TCP front —
supervised by ``resilience/supervise.ReplicaSupervisor``, routed by
the asyncio :class:`FleetRouter` in this module. The control-plane
design follows the execution-templates blueprint (PAPERS.md): every
expensive decision is cached — plans in each replica's session cache,
compiled programs in the SHARED disk AOT store (``cache.dir``), and
routing affinity on the parameterized plan digest — so per-request
work is cheap and replica membership changes are routine:

- **Affinity routing** — requests hash by their parameterized plan
  digest (``sql/params.plan_key`` via a planning-only session when the
  router has one, else a literal-stripped template hash): same-template
  traffic lands on the same replica and batches fat there (the
  server's template batching groups by the same digest). Rendezvous
  hashing keeps the map stable under membership churn — one replica's
  departure remaps only its own keys.

- **Health gating** — a replica is admitted only while BOTH health
  sources agree: the app-level ``op: ping`` (answered off-queue by the
  TCP handler: engine-thread liveness, queue depth, draining flag) and
  the watchdog-heartbeat ages embedded in its metrics-snapshot file
  (``fold_child_snapshot`` semantics — a wedged engine with a live
  event loop still answers pings, but its heartbeat ages give it
  away). ``serve.fleet.ping_misses`` consecutive misses eject it from
  the ring; a clean probe of the relaunched incarnation (fresh
  announce file, new port) re-admits it.

- **Zero-loss / zero-double** — every accepted request is journaled
  (id + tenant + digest, atomic ``integrity.write_json_atomic``)
  BEFORE dispatch. A dead replica's in-flight requests fail over:
  the connection loss rejects their client futures and the router
  redelivers to healthy peers (read-only queries are safely
  re-executable), duplicate-suppressed by request id — the journal's
  first FINAL outcome per id wins, later arrivals are counted, never
  re-answered. Departure notices (``server-stopping`` sheds from a
  draining replica, connection-deadline sheds) are redelivered, not
  answered. ``RequestJournal.verify()`` proves the invariant: zero
  accepted-but-unanswered, zero double-answered.

- **Router-level shedding** — admission projects fleet capacity
  (healthy replicas x ``serve.max_queue``, or the explicit
  ``serve.fleet.max_pending``): past it the router sheds with
  ``router-admission`` BEFORE any replica browns out, and with
  ``no-healthy-replica`` when the ring is empty past the bounded
  member wait.

Config: ``serve.fleet.*`` keys (utils/config.py). Chaos drills:
``tools/ndsload.py --fleet N --kill replica=1@2.0,KILL``. Gate:
``tools/fleet_serve_check.py`` (tier-1 via static_checks).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import re
import time

from nds_tpu.analysis import locksan
from nds_tpu.obs import metrics as obs_metrics

DEFAULT_PING_INTERVAL_S = 0.5
DEFAULT_PING_TIMEOUT_S = 5.0
DEFAULT_PING_MISSES = 3
DEFAULT_REQUEST_TIMEOUT_S = 600.0
DEFAULT_REDELIVER_MAX = 4
DEFAULT_MEMBER_WAIT_S = 30.0
DEFAULT_HB_STALE_S = 0.0  # 0 = snapshot-liveness gating off

# write/connect deadline inside the client (NDS118: every
# cross-process await in this package is bounded)
_IO_TIMEOUT_S = 30.0
# the reader parks on readline between responses; re-arm the deadline
# instead of holding one forever
_READ_PARK_S = 120.0

_LITERAL_RE = re.compile(r"'(?:[^']|'')*'|\b\d+(?:\.\d+)?\b")


def template_digest(suite: str, sql: str) -> str:
    """Literal-stripped template fingerprint — the plannerless routing
    fallback. Same-template literal variants collapse to one digest
    (affinity only: the replica's own ``plan_key`` still decides
    batching and compilation)."""
    stripped = _LITERAL_RE.sub("?", sql)
    return hashlib.sha256(
        f"{suite}|{stripped}".encode()).hexdigest()[:16]


def make_planner(sessions: dict):
    """True ``plan_key``-digest planner over planning-only sessions
    ({suite: engine.session.Session} with the warehouse registered).
    Call it through the router's single planning thread — session plan
    caches are not re-entrant."""
    def planner(suite: str, sql: str) -> "str | None":
        s = sessions.get(suite)
        if s is None:
            return None
        from nds_tpu.sql import params as sqlparams
        planned = s._planned_for((sql, s._views_signature()), sql)
        if isinstance(planned, tuple):
            return None
        key = sqlparams.plan_key(planned)
        return key[1] if key else None
    return planner


class RequestJournal:
    """Accepted-request ledger with first-final-outcome-wins
    duplicate suppression, persisted atomically on every mutation so
    a router crash loses no accounting."""

    def __init__(self, path: str):
        self.path = path
        self._lock = locksan.lock("serve.RequestJournal._lock")
        self.accepted: dict = {}
        self.outcomes: dict = {}

    def _persist(self, accepted: dict, outcomes: dict) -> None:
        # ledger dicts arrive as parameters so every read of the
        # shared state stays lexically under the caller's ``with
        # self._lock`` block (NDSR201 guard inference)
        from nds_tpu.io.integrity import write_json_atomic
        write_json_atomic(self.path, {
            "accepted": accepted,
            "outcomes": {rid: {k: v for k, v in o.items()
                               if k != "response"}
                         for rid, o in outcomes.items()}})

    def accept(self, rid: str, tenant: str, suite: str, qname: str,
               digest: "str | None") -> None:
        with self._lock:
            self.accepted[rid] = {"tenant": tenant, "suite": suite,
                                  "qname": qname, "digest": digest,
                                  "assignments": [], "ts": time.time()}
            self._persist(self.accepted, self.outcomes)

    def assign(self, rid: str, replica: str) -> None:
        with self._lock:
            rec = self.accepted.get(rid)
            if rec is not None:
                rec["assignments"].append(replica)
                self._persist(self.accepted, self.outcomes)

    def settle(self, rid: str, resp: dict) -> dict:
        """Record a FINAL outcome for ``rid``; returns the canonical
        response — the first one recorded. A duplicate (a drained
        replica's late answer racing the redelivered one) is counted,
        never re-answered."""
        with self._lock:
            prev = self.outcomes.get(rid)
            if prev is not None:
                prev["duplicates"] = prev.get("duplicates", 0) + 1
                self._persist(self.accepted, self.outcomes)
                obs_metrics.counter(
                    "fleet_duplicate_answers_total").inc()
                return dict(prev["response"])
            self.outcomes[rid] = {
                "status": resp.get("status"),
                "digest": resp.get("digest"),
                "replica": resp.get("replica"),
                "qname": resp.get("qname"),
                "response": dict(resp), "ts": time.time()}
            self._persist(self.accepted, self.outcomes)
            return resp

    def verify(self) -> dict:
        """The fleet gate's proof obligation: every accepted request
        has exactly one final outcome."""
        with self._lock:
            lost = [rid for rid in self.accepted
                    if rid not in self.outcomes]
            double = [rid for rid, o in self.outcomes.items()
                      if o.get("duplicates")]
            return {"accepted": len(self.accepted),
                    "settled": len(self.outcomes),
                    "lost": lost, "double": double}


class ReplicaClient:
    """One multiplexed JSON-lines connection to a replica: requests
    tagged by id, a reader task dispatches responses to per-request
    futures. Connection loss rejects every pending future — the
    router's redelivery loop is the recovery."""

    def __init__(self, name: str, host: str, port: int,
                 incarnation: int = 0):
        self.name = name
        self.host = host
        self.port = port
        self.incarnation = incarnation
        self._reader = None
        self._writer = None
        self._reader_task = None
        self._wlock = asyncio.Lock()
        self._pending: "dict[str, asyncio.Future]" = {}
        self._seq = 0

    @property
    def connected(self) -> bool:
        return (self._writer is not None
                and not self._writer.is_closing())

    async def connect(self, timeout: float = _IO_TIMEOUT_S) -> None:
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port),
            timeout=timeout)
        self._reader_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                try:
                    line = await asyncio.wait_for(
                        self._reader.readline(), timeout=_READ_PARK_S)
                except asyncio.TimeoutError:
                    continue  # idle between responses: re-arm
                if not line:
                    break
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                fut = self._pending.pop(doc.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(doc)
        except (OSError, asyncio.CancelledError):
            pass
        finally:
            w, self._writer = self._writer, None
            if w is not None:
                w.close()
            self._fail_pending(ConnectionError(
                f"replica {self.name} connection lost"))

    def _fail_pending(self, exc: Exception) -> None:
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)

    async def request(self, doc: dict, timeout: float) -> dict:
        """Send one id-tagged request and await its response (raises
        ConnectionError / asyncio.TimeoutError on a dead or silent
        replica — redelivery is the caller's move)."""
        if not self.connected:
            raise ConnectionError(f"replica {self.name} not connected")
        rid = doc["id"]
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            payload = (json.dumps(doc) + "\n").encode()
            async with self._wlock:
                self._writer.write(payload)
                await asyncio.wait_for(self._writer.drain(),
                                       timeout=_IO_TIMEOUT_S)
            return await asyncio.wait_for(fut, timeout=timeout)
        finally:
            self._pending.pop(rid, None)

    async def ping(self, timeout: float) -> dict:
        self._seq += 1
        return await self.request(
            {"op": "ping", "id": f"_ping-{self.name}-{self._seq}"},
            timeout)

    async def close(self) -> None:
        task, self._reader_task = self._reader_task, None
        if task is not None:
            task.cancel()
            try:
                await asyncio.wait_for(
                    asyncio.gather(task, return_exceptions=True),
                    timeout=5.0)
            except asyncio.TimeoutError:
                pass
        w, self._writer = self._writer, None
        if w is not None:
            w.close()
        self._fail_pending(ConnectionError(
            f"replica {self.name} client closed"))


class FleetRouter:
    """Digest-affinity router over the replica ring (module
    docstring). Drive it from inside a running event loop:
    ``await router.start()``, ``await router.submit(doc)``,
    ``await router.stop()``."""

    def __init__(self, fleet_dir: str, config=None, supervisor=None,
                 planner=None):
        from nds_tpu.utils.config import EngineConfig
        self.config = config or EngineConfig()
        self.fleet_dir = fleet_dir
        os.makedirs(fleet_dir, exist_ok=True)
        self.journal = RequestJournal(
            os.path.join(fleet_dir, "fleet_journal.json"))
        self.supervisor = supervisor
        self.planner = planner
        self.ping_interval = self._cfg_float(
            "serve.fleet.ping_interval_s", DEFAULT_PING_INTERVAL_S)
        self.ping_timeout = self._cfg_float(
            "serve.fleet.ping_timeout_s", DEFAULT_PING_TIMEOUT_S)
        self.ping_misses = int(self._cfg_float(
            "serve.fleet.ping_misses", DEFAULT_PING_MISSES))
        self.request_timeout = self._cfg_float(
            "serve.fleet.request_timeout_s", DEFAULT_REQUEST_TIMEOUT_S)
        self.redeliver_max = int(self._cfg_float(
            "serve.fleet.redeliver_max", DEFAULT_REDELIVER_MAX))
        self.max_pending = int(self._cfg_float(
            "serve.fleet.max_pending", 0))
        self.member_wait = self._cfg_float(
            "serve.fleet.member_wait_s", DEFAULT_MEMBER_WAIT_S)
        self.hb_stale_s = self._cfg_float(
            "serve.fleet.hb_stale_s", DEFAULT_HB_STALE_S)
        self._members: "dict[str, dict]" = {}
        self._pending = 0
        self._seq = 0
        self._loop = None
        self._health_task = None
        self._plan_pool = None
        if supervisor is not None:
            supervisor.on_membership(up=self._on_up,
                                     down=self._on_down)

    def _cfg_float(self, key: str, default: float) -> float:
        try:
            return float(self.config.get(key, default))
        except (TypeError, ValueError):
            return default

    # ----------------------------------------------------- membership

    def add_replica(self, name: str, announce_path: str,
                    hb_path: "str | None" = None) -> None:
        """Register a ring member (before OR after start(): a replica
        added mid-run is probed and admitted by the health loop —
        late joiners are routine, not special)."""
        self._members[name] = {
            "name": name, "announce": announce_path,
            "hb_path": hb_path, "client": None, "healthy": False,
            "draining": False, "misses": 0, "queue_depth": 0}

    def _on_down(self, name: str, reason: str) -> None:
        # called from the supervisor's poll THREAD: marshal onto the
        # loop — member state is loop-confined
        loop = self._loop
        if loop is not None:
            loop.call_soon_threadsafe(self._mark_down, name, reason)

    def _on_up(self, name: str, incarnation: int) -> None:
        # relaunch is a fact, health is not: the health loop probes
        # the new incarnation's announce before re-admitting
        print(f"[fleet] {name} relaunched as incarnation "
              f"{incarnation}; awaiting health probe", flush=True)

    def _mark_down(self, name: str, reason: str) -> None:
        m = self._members.get(name)
        if m is None:
            return
        if m["healthy"]:
            obs_metrics.counter("fleet_ejections_total").inc()
            print(f"[fleet] ejecting {name}: {reason}", flush=True)
        m["healthy"] = False
        m["misses"] = self.ping_misses

    def healthy_replicas(self) -> "list[str]":
        return [m["name"] for m in self._members.values()
                if m["healthy"] and not m["draining"]]

    # ------------------------------------------------------ lifecycle

    async def start(self) -> "FleetRouter":
        import concurrent.futures
        self._loop = asyncio.get_running_loop()
        # single planning thread: session plan caches are not
        # re-entrant, and one thread keeps the cache hot in order
        self._plan_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="fleet-planner")
        self._health_task = asyncio.ensure_future(self._health_loop())
        return self

    async def stop(self) -> None:
        task, self._health_task = self._health_task, None
        if task is not None:
            task.cancel()
            try:
                await asyncio.wait_for(
                    asyncio.gather(task, return_exceptions=True),
                    timeout=5.0)
            except asyncio.TimeoutError:
                pass
        for m in self._members.values():
            if m["client"] is not None:
                await m["client"].close()
                m["client"] = None
            m["healthy"] = False
        if self._plan_pool is not None:
            self._plan_pool.shutdown(wait=False)

    async def wait_admitted(self, n: int, timeout: float = 60.0
                            ) -> bool:
        """Block until ``n`` ring members are healthy (gate/test
        rendezvous)."""
        deadline = self._loop.time() + timeout
        while self._loop.time() < deadline:
            if len(self.healthy_replicas()) >= n:
                return True
            await asyncio.sleep(self.ping_interval / 2)
        return False

    # --------------------------------------------------------- health

    async def _health_loop(self) -> None:
        while True:
            for m in list(self._members.values()):
                try:
                    await self._probe(m)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # noqa: BLE001 - keep probing
                    self._miss(m, f"{type(exc).__name__}: {exc}")
            await asyncio.sleep(self.ping_interval)

    @staticmethod
    def _read_json(path: "str | None") -> "dict | None":
        if not path:
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    @staticmethod
    def _hb_age(path: "str | None") -> "float | None":
        """Effective heartbeat age of a replica's snapshot file:
        (now - mtime) + youngest in-file age — the supervisor's
        liveness definition, read from the router's side."""
        if not path:
            return None
        try:
            mtime = os.stat(path).st_mtime
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        hbs = doc.get("heartbeats") or {}
        if not hbs:
            return None
        youngest = min(h.get("age_s", 0.0) for h in hbs.values())
        return (time.time() - mtime) + youngest

    async def _probe(self, m: dict) -> None:
        # announce file read off-loop (NDS115: no blocking IO in
        # coroutines)
        ann = await self._loop.run_in_executor(
            None, self._read_json, m["announce"])
        if ann is None and m["client"] is None:
            self._miss(m, "no announce file")
            return
        cl = m["client"]
        if ann is not None and (
                cl is None or not cl.connected
                or int(ann.get("port", -1)) != cl.port
                or int(ann.get("incarnation", 0)) != cl.incarnation):
            # new endpoint (first sight, reconnect, or a resumed
            # incarnation's fresh port): swap the client
            fresh = ReplicaClient(
                m["name"], str(ann.get("host", "127.0.0.1")),
                int(ann["port"]), int(ann.get("incarnation", 0)))
            try:
                await fresh.connect(self.ping_timeout)
            except (OSError, asyncio.TimeoutError):
                self._miss(m, "connect failed")
                return
            if cl is not None:
                await cl.close()
            m["client"] = cl = fresh
        if cl is None or not cl.connected:
            self._miss(m, "not connected")
            return
        try:
            pong = await cl.ping(self.ping_timeout)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            self._miss(m, "ping miss")
            return
        if not pong.get("engine_alive"):
            self._miss(m, "engine thread dead")
            return
        if self.hb_stale_s > 0:
            age = await self._loop.run_in_executor(
                None, self._hb_age, m["hb_path"])
            if age is not None and age > self.hb_stale_s:
                # the engine answers pings but nothing beats: wedged
                self._miss(m, f"stale heartbeats ({age:.1f}s)")
                return
        m["draining"] = bool(pong.get("draining"))
        m["queue_depth"] = int(pong.get("queue_depth") or 0)
        m["misses"] = 0
        if not m["healthy"] and not m["draining"]:
            m["healthy"] = True
            obs_metrics.counter("fleet_admissions_total").inc()
            print(f"[fleet] admitted {m['name']} "
                  f"(incarnation {cl.incarnation}, port {cl.port})",
                  flush=True)

    def _miss(self, m: dict, why: str) -> None:
        m["misses"] += 1
        if m["misses"] >= self.ping_misses and m["healthy"]:
            m["healthy"] = False
            obs_metrics.counter("fleet_ejections_total").inc()
            print(f"[fleet] ejecting {m['name']}: {why} "
                  f"({m['misses']} consecutive misses)", flush=True)

    # -------------------------------------------------------- routing

    def _ring(self, exclude: "set | None" = None) -> list:
        return [m for m in self._members.values()
                if m["healthy"] and not m["draining"]
                and m["client"] is not None and m["client"].connected
                and m["name"] not in (exclude or ())]

    def _pick(self, digest: "str | None",
              exclude: "set | None" = None) -> "dict | None":
        """Rendezvous (highest-random-weight) hash: each digest ranks
        every member independently, so one departure remaps only the
        keys it owned."""
        ring = self._ring(exclude)
        if not ring:
            return None
        key = digest or "_none"
        return max(ring, key=lambda m: hashlib.sha256(
            f"{key}|{m['name']}".encode()).digest())

    def _capacity(self) -> int:
        if self.max_pending > 0:
            return self.max_pending
        from nds_tpu.serve.server import DEFAULT_MAX_QUEUE
        try:
            per = int(self.config.get_int("serve.max_queue",
                                          DEFAULT_MAX_QUEUE))
        except (TypeError, ValueError):
            per = DEFAULT_MAX_QUEUE
        return max(1, len(self._ring())) * max(1, per)

    @staticmethod
    def _is_departure(resp: dict) -> bool:
        """A shed that means "this replica is leaving", not "this
        request was answered": redeliver, don't settle."""
        if resp.get("status") != "shed":
            return False
        reason = str(resp.get("shed_reason") or "")
        return (reason.startswith("server-stopping")
                or reason.startswith("conn-read-timeout"))

    async def _digest(self, doc: dict) -> "str | None":
        if self.planner is not None:
            try:
                return await self._loop.run_in_executor(
                    self._plan_pool, self.planner,
                    str(doc.get("suite", "")), str(doc["sql"]))
            except Exception:  # noqa: BLE001 - affinity only
                pass
        return template_digest(str(doc.get("suite", "")),
                               str(doc["sql"]))

    async def _acquire(self, digest: "str | None",
                       tried: set) -> "dict | None":
        """A healthy member for ``digest``, preferring ones this
        request has not failed on; waits (bounded) through membership
        gaps — a mid-failover lull is a wait, not a shed."""
        deadline = self._loop.time() + self.member_wait
        while True:
            m = self._pick(digest, tried)
            if m is None and tried:
                # every healthy member already failed this request
                # once: allow another lap rather than shedding while
                # capacity exists
                m = self._pick(digest, None)
            if m is not None:
                return m
            if self._loop.time() >= deadline:
                return None
            await asyncio.sleep(self.ping_interval / 2)

    def _shed(self, doc: dict, rid: str, reason: str) -> dict:
        obs_metrics.counter("fleet_shed_total").inc()
        return {"status": "shed", "qname": str(doc.get("qname", "")),
                "tenant": str(doc.get("tenant", "")),
                "shed_reason": reason, "id": rid}

    async def submit(self, doc: dict) -> dict:
        """Route one request dict (the TCP front's request shape)
        through the ring; returns the response dict. Exactly one
        final answer per request id, journal-enforced."""
        self._seq += 1
        rid = str(doc.get("id") or f"fr-{self._seq}")
        doc = dict(doc)
        doc["id"] = rid
        cap = self._capacity()
        if self._pending >= cap:
            # shed BEFORE accept: admission control is not a lost
            # request, the client was answered synchronously
            return self._shed(
                doc, rid, f"router-admission:pending:{self._pending}"
                          f">=cap:{cap}")
        self._pending += 1
        obs_metrics.gauge("fleet_pending").set(self._pending)
        try:
            digest = await self._digest(doc)
            await self._loop.run_in_executor(
                None, self.journal.accept, rid,
                str(doc.get("tenant", "")), str(doc.get("suite", "")),
                str(doc.get("qname", "")), digest)
            tried: set = set()
            for _attempt in range(self.redeliver_max + 1):
                m = await self._acquire(digest, tried)
                if m is None:
                    break
                name = m["name"]
                await self._loop.run_in_executor(
                    None, self.journal.assign, rid, name)
                try:
                    resp = await m["client"].request(
                        doc, self.request_timeout)
                except (ConnectionError, OSError,
                        asyncio.TimeoutError):
                    # dead or silent replica: the journal knows this
                    # request; redeliver to a healthy peer
                    obs_metrics.counter(
                        "fleet_redelivered_total").inc()
                    tried.add(name)
                    continue
                if self._is_departure(resp):
                    obs_metrics.counter(
                        "fleet_redelivered_total").inc()
                    tried.add(name)
                    continue
                final = await self._loop.run_in_executor(
                    None, self.journal.settle, rid, resp)
                obs_metrics.counter(obs_metrics.labeled(
                    "fleet_requests_total",
                    replica=str(final.get("replica") or name))).inc()
                return final
            resp = self._shed(doc, rid,
                              f"redeliver-exhausted:"
                              f"{self.redeliver_max + 1}")
            return await self._loop.run_in_executor(
                None, self.journal.settle, rid, resp)
        finally:
            self._pending -= 1
            obs_metrics.gauge("fleet_pending").set(self._pending)


def launch_fleet(fleet_dir: str, names: "list[str]",
                 replica_argv, config=None, supervisor=None,
                 planner=None, stall_s: "float | None" = 10.0,
                 max_restarts: int = 2, max_resumes: int = 3,
                 startup_grace_s: "float | None" = None):
    """Wire a supervised fleet: specs + ReplicaSupervisor +
    FleetRouter, announce/hb lanes under ``fleet_dir``. Returns
    ``(supervisor, router)`` — caller starts both
    (``supervisor.start()``; ``await router.start()``).

    ``replica_argv(name, announce_path, incarnation)`` builds each
    child's argv (``python -m nds_tpu.serve.replica ...``)."""
    from nds_tpu.resilience.supervise import (
        ReplicaSpec, ReplicaSupervisor,
    )
    ann_dir = os.path.join(fleet_dir, "announce")
    hb_dir = os.path.join(fleet_dir, "hb")
    os.makedirs(ann_dir, exist_ok=True)
    os.makedirs(hb_dir, exist_ok=True)
    specs = []
    for name in names:
        ann = os.path.join(ann_dir, f"{name}.json")
        specs.append(ReplicaSpec(
            name=name,
            make_cmd=(lambda inc, n=name, a=ann:
                      replica_argv(n, a, inc)),
            hb_path=os.path.join(hb_dir, f"{name}.json"),
            announce_path=ann))
    sup = supervisor or ReplicaSupervisor(
        specs, fleet_dir, stall_s=stall_s, max_restarts=max_restarts,
        max_resumes=max_resumes, startup_grace_s=startup_grace_s)
    router = FleetRouter(fleet_dir, config=config, supervisor=sup,
                         planner=planner)
    for spec in specs:
        router.add_replica(spec.name, spec.announce_path,
                           hb_path=spec.hb_path)
    return sup, router


def scale_out(sup, router, fleet_dir: str, name: str,
              replica_argv) -> None:
    """Add one replica to a RUNNING ``launch_fleet`` fleet: same
    announce/hb lanes, same argv factory. The joiner warms from the
    shared AOT store (the fleet already paid every compile) and the
    router health-probes it before routing traffic its way."""
    from nds_tpu.resilience.supervise import ReplicaSpec
    ann = os.path.join(fleet_dir, "announce", f"{name}.json")
    spec = ReplicaSpec(
        name=name,
        make_cmd=(lambda inc, n=name, a=ann: replica_argv(n, a, inc)),
        hb_path=os.path.join(fleet_dir, "hb", f"{name}.json"),
        announce_path=ann)
    router.add_replica(spec.name, spec.announce_path,
                       hb_path=spec.hb_path)
    sup.add_replica(spec)
