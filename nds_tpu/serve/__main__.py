"""Standalone server launcher: warehouse in, TCP JSON-lines out.

    python -m nds_tpu.serve --port 9321 \
        --nds_h_data /path/to/tpch_wh [--nds_data /path/to/tpcds_wh] \
        --backend tpu --cache_dir /path/to/plancache \
        --summary_dir /path/to/serve_json

Loads each suite's warehouse into its namespace (TPC-H and TPC-DS both
define ``customer`` — they never share a registry), starts the engine
thread + asyncio TCP front, and serves until SIGINT/SIGTERM. Drive it
with ``tools/ndsload.py --port ...`` (README "Serving"). ``--port 0``
picks a free port and prints it — the form the smoke drives use.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys


def _load_suite(server, suite: str, data_dir: str, fmt: str) -> int:
    from nds_tpu.io import csv_io
    if suite == "nds_h":
        from nds_tpu.nds_h.schema import get_schemas
    else:
        from nds_tpu.nds.schema import get_schemas
    schemas = get_schemas()
    n = 0
    for name, schema in schemas.items():
        tdir = os.path.join(data_dir, name)
        ext = csv_io.FORMAT_EXT.get(fmt, ".parquet")
        if os.path.isdir(tdir):
            paths = sorted(
                os.path.join(root, f)
                for root, _dirs, files in os.walk(tdir)
                for f in files if f.endswith(ext))
        else:
            single = os.path.join(data_dir, f"{name}{ext}")
            if not os.path.exists(single):
                continue
            paths = [single]
        if not paths:
            continue
        server.register_table(
            csv_io.read_table_fmt(paths, name, schema, fmt), suite)
        n += 1
    return n


async def _serve(server, host: str, port: int) -> None:
    import signal

    from nds_tpu.serve.net import start_tcp
    tcp = await start_tcp(server, host, port)
    bound = tcp.sockets[0].getsockname()[1]
    print(f"[serve] listening on {host}:{bound}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        # loop-native handlers: the default KeyboardInterrupt path can
        # land mid-callback and skip the close/drain below
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    print("[serve] draining", flush=True)
    tcp.close()
    await asyncio.wait_for(tcp.wait_closed(), timeout=30.0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9321,
                    help="0 picks a free port (printed at startup)")
    ap.add_argument("--nds_h_data", help="NDS-H (TPC-H) warehouse dir")
    ap.add_argument("--nds_data", help="NDS (TPC-DS) warehouse dir")
    ap.add_argument("--input_format", default="parquet")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--cache_dir",
                    help="persistent AOT plan cache (cache.dir)")
    ap.add_argument("--summary_dir",
                    help="per-request BenchReport summaries "
                         "(serve.summary_dir)")
    ap.add_argument("--max_queue", type=int, default=None)
    ap.add_argument("--deadline_ms", type=int, default=None)
    ap.add_argument("--template", help="engine template file")
    ap.add_argument("--property_file", help="k=v property overrides")
    args = ap.parse_args(argv)
    if not args.nds_h_data and not args.nds_data:
        ap.error("at least one of --nds_h_data/--nds_data is required")

    from nds_tpu.serve import QueryServer
    from nds_tpu.utils.config import EngineConfig
    overrides = {"engine.backend": args.backend}
    if args.cache_dir:
        overrides["cache.dir"] = args.cache_dir
    if args.summary_dir:
        overrides["serve.summary_dir"] = args.summary_dir
    if args.max_queue is not None:
        overrides["serve.max_queue"] = str(args.max_queue)
    if args.deadline_ms is not None:
        overrides["serve.deadline_ms"] = str(args.deadline_ms)
    cfg = EngineConfig(args.template, args.property_file, overrides)
    server = QueryServer(cfg)
    for suite, d in (("nds_h", args.nds_h_data),
                     ("nds", args.nds_data)):
        if d:
            n = _load_suite(server, suite, d, args.input_format)
            print(f"[serve] {suite}: {n} tables from {d}", flush=True)
    server.start()
    try:
        asyncio.run(_serve(server, args.host, args.port))
    finally:
        server.stop()
        print(f"[serve] stopped: {server.stats}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
