"""Host-staged plan splitting: keep every compiled program small.

The widest TPC-DS plans (q64's 18-relation CTE referenced twice, q72's
11-relation M:N join chain) trace to 25k-55k jaxpr equations in ONE
shard_map program; XLA's compile memory and time grow superlinearly
with program size, and on an 8-device mesh the q64/q72 compiles
exceeded 130 GB host RAM (VERDICT r4 weak #2). On a real pod that bill
moves to the compile service — the program, not the host, is the
problem.

The fix is structural, the same move the reference's engine makes when
Spark materializes a shuffle boundary: CUT the plan at a subtree
boundary, run the subtree as its own program, stage its (compacted)
result on the host as a temp table, and let the remainder scan that
table. Each resulting program is a fraction of the original's
compile cost; a shared CTE body (q64's cross_sales, referenced by both
year channels) is staged ONCE and scanned twice — a runtime win on top
of the compile fix.

Cuts happen at DerivedScan children (CTE/derived-table bodies — single
binding, exact output list) and at Join/SemiJoin inputs (multi-binding:
the staged table carries every column any ancestor references, found by
liveness over `plan.all_exprs` plus the implicit readers). Staging
recurses: an oversized staged subtree is itself split when executed.
"""

from __future__ import annotations

from dataclasses import replace as dc_replace

import numpy as np

from nds_tpu.engine.types import BoolType, Schema
from nds_tpu.io.host_table import HostTable, from_arrays
from nds_tpu.obs.trace import get_tracer
from nds_tpu.sql import ir
from nds_tpu.sql import plan as P

# subtree weights: cuts only make sense when both halves stay compileable
MIN_CUT_WEIGHT = 6


def stage_temp_name(plan_digest: str, index: int) -> str:
    """Deterministic temp-table name for the index-th cut of a plan.

    The digest (cache/fingerprint.plan_digest of the ORIGINAL plan)
    replaces the old per-executor counter: staged buffer keys embed the
    temp name, so the persistent AOT plan cache can only serve a
    staged main program across processes when identical plans stage
    identically-named temps. Distinct plans yield distinct digests, so
    names stay collision-free within an executor."""
    return f"__stage_{plan_digest}_{index}"


def _uniq_nodes(*roots) -> set:
    seen = set()
    for r in roots:
        for n in P.walk_plan(r):
            seen.add(id(n))
    return seen


def plan_weight(planned: P.PlannedQuery) -> int:
    """Deduplicated plan-node count (shared CTE bodies count once, like
    the trace cache treats them)."""
    return len(_uniq_nodes(planned.root, *planned.scalar_subplans))


def _subtree_weight(node: P.Node) -> int:
    return len(_uniq_nodes(node))


def _col_refs(e) -> "set[tuple[str, str]]":
    return {(x.binding, x.name) for x in ir.walk(e)
            if isinstance(x, ir.ColRef)}


def _exposed(node: P.Node) -> dict:
    """{(binding, name): dtype} the node's runtime context exposes
    upward — mirrors each _run_* method's DCtx construction. This, NOT
    the set of bindings inside the subtree, bounds what a cut can
    stage: bindings are not instance-unique (q14 scans catalog_sales in
    three separate channel subtrees), so outside references must be
    intersected with the cut root's actual exposure."""
    if isinstance(node, P.StagedScan):
        return {(b, n): dt for b, n, _m, dt in node.cols}
    if isinstance(node, (P.Scan, P.DerivedScan, P.Project, P.Aggregate,
                         P.Distinct)):
        return {(node.binding, n): dt for n, dt in node.output}
    if isinstance(node, P.Join):
        d = _exposed(node.left)
        d.update(_exposed(node.right))
        return d
    if isinstance(node, (P.SemiJoin, P.SetOp)):
        return _exposed(node.left)
    if isinstance(node, P.Window):
        d = _exposed(node.child)
        d.update({(node.binding, n): s.dtype for n, s in node.specs})
        return d
    return _exposed(node.child)  # Filter / Sort / Limit passthrough


def _live_cols(planned: P.PlannedQuery, cut: P.Node) -> list:
    """(binding, name, dtype) triples ancestors read from the cut
    subtree: explicit ColRefs in every node OUTSIDE the subtree plus
    implicit whole-output readers (DerivedScan/Distinct/SetOp over the
    cut), intersected with what the cut's root context exposes."""
    if planned.root is cut:
        raise ValueError("cut may not be the plan root")
    inside = _uniq_nodes(cut)
    exposed = _exposed(cut)
    refs = set()

    def note(b, name):
        if (b, name) in exposed:
            refs.add((b, name))

    roots = [planned.root] + list(planned.scalar_subplans)
    for root in roots:
        if id(root) in inside:
            continue
        for node in P.walk_plan(root):
            if id(node) in inside:
                continue
            for e in P.all_exprs(node):
                for b, name in _col_refs(e):
                    note(b, name)
            # implicit whole-output readers
            if isinstance(node, P.DerivedScan) and node.child is cut:
                for name, _dt in cut.output:
                    note(cut.binding, name)
            elif isinstance(node, P.Distinct) and node.child is cut:
                for name, _dt in node.output:
                    note(node.binding, name)
            elif isinstance(node, P.SetOp):
                for side in (node.left, node.right):
                    if side is cut:
                        for name, _dt in side.output:
                            note(side.binding, name)
    # run_query reads the plan root's output columns; when the cut sits
    # under a passthrough root (Limit/Sort/Filter) those come from the
    # cut's exposure
    for name, _dt in planned.root.output:
        note(planned.root.binding, name)
    return sorted((b, n, exposed[(b, n)]) for b, n in refs)


def _candidates(planned: P.PlannedQuery):
    """Cut candidates: DerivedScan children and Join/SemiJoin inputs.
    DerivedScan children come first so ties prefer the clean
    single-binding boundary (and shared CTE bodies dedupe)."""
    derived, joins = [], []
    seen = set()
    for node in P.walk_plan(planned.root):
        if isinstance(node, P.DerivedScan):
            c = node.child
            if id(c) not in seen and not isinstance(c, P.StagedScan):
                seen.add(id(c))
                derived.append(c)
        elif isinstance(node, (P.Join, P.SemiJoin)):
            for c in (node.left, node.right):
                if id(c) not in seen and not isinstance(c, P.StagedScan):
                    seen.add(id(c))
                    joins.append(c)
    return derived + joins


def choose_cut(planned: P.PlannedQuery):
    """The candidate whose weight is closest to half the plan's —
    balanced halves minimize the larger program. None when no cut can
    make progress."""
    total = plan_weight(planned)
    best, best_score = None, None
    for i, cand in enumerate(_candidates(planned)):
        w = _subtree_weight(cand)
        if w < MIN_CUT_WEIGHT or w > total - 4:
            continue
        score = (abs(w - total / 2), i)
        if best_score is None or score < best_score:
            best, best_score = cand, score
    return best


def _mangle(b: str, name: str) -> str:
    return f"{b}__{name}"


def build_stage(planned: P.PlannedQuery, cut: P.Node, temp_name: str):
    """(sub_planned, staged_main_planned).

    sub_planned projects the cut subtree's live columns under mangled
    names; the main plan gets every reference to `cut` replaced by a
    StagedScan of `temp_name` that restores original (binding, name)
    addresses. Scalar subplans are carried into the sub program so
    ScalarRef indices keep their meaning."""
    with get_tracer().span("stage.split", temp=temp_name,
                           cut_weight=_subtree_weight(cut)):
        return _build_stage(planned, cut, temp_name)


def _build_stage(planned: P.PlannedQuery, cut: P.Node, temp_name: str):
    live = _live_cols(planned, cut)
    if not live:
        raise ValueError("cut subtree has no live outputs")
    exprs = [(_mangle(b, n), ir.ColRef(b, n, dtype=dt))
             for b, n, dt in live]
    sub_root = P.Project(child=cut, exprs=exprs, binding="__stage_out")
    sub = P.PlannedQuery(
        root=sub_root,
        scalar_subplans=list(planned.scalar_subplans),
        column_names=[n for n, _ in exprs])

    scan = P.Scan(table=temp_name, binding=f"__{temp_name}",
                  output=[(_mangle(b, n), dt) for b, n, dt in live])
    staged = P.StagedScan(
        child=scan,
        cols=[(b, n, _mangle(b, n), dt) for b, n, dt in live],
        binding=cut.binding,
        output=list(cut.output))

    main_root = _replace(planned.root, cut, staged, {})
    main = P.PlannedQuery(root=main_root,
                          scalar_subplans=list(planned.scalar_subplans),
                          column_names=list(planned.column_names))
    return sub, main


def _replace(node: P.Node, cut: P.Node, repl: P.Node, memo: dict):
    """Copy-on-write subtree replacement: rebuild only the spine above
    `cut`; untouched subtrees (and shared references) stay shared."""
    if node is cut:
        return repl
    nid = id(node)
    if nid in memo:
        return memo[nid]
    changed = {}
    for attr in ("child", "left", "right"):
        c = getattr(node, attr, None)
        if isinstance(c, P.Node):
            r = _replace(c, cut, repl, memo)
            if r is not c:
                changed[attr] = r
    out = dc_replace(node, **changed) if changed else node
    # ndslint: waive[NDS101] -- memo lives for one _replace() pass over a live plan
    memo[nid] = out
    return out


def result_to_host_table(name: str, rt) -> HostTable:
    """Lossless ResultTable -> HostTable: decimals stay scaled int64,
    dates stay epoch days, strings re-dictionary-encode, null masks
    carry over."""
    fields, arrays = [], {}
    for cname, arr, dt, valid in zip(rt.names, rt.cols, rt.dtypes,
                                     rt.valids):
        dt = dt if dt is not None else BoolType()
        fields.append((cname, dt, valid is not None))
        arrays[cname] = np.asarray(arr)
        if valid is not None:
            arrays[cname + "#null"] = np.asarray(valid, dtype=bool)
    return from_arrays(name, Schema.of(*fields), arrays)
