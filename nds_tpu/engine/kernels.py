"""TPU-shaped relational kernels + the planner's kernel-selection pass.

The static-shape engine's original operators fight the hardware in three
places the BENCH_r02 traces point at (ROADMAP item 2): every gather join
pays a full-table ``lax.sort`` + ``searchsorted`` probe even when the
build side is a small dimension table with host-known key bounds; every
EXISTS chain (q21/q22) runs the same sort machinery just to answer a
membership question; and grouped min/max lower to ``segment_min/max``
scatters, which XLA emulates element-at-a-time for 64-bit operands on
TPU. This module is the TQP-style answer ("Query Processing on Tensor
Computation Runtimes", PAPERS.md): reformulate the hot operators as
dense gathers, one-hot matmuls that ride the MXU, radix-partitioned
batched sorts, and segmented scans that ride the VPU.

Kernel catalog (selection rules in ``annotate``; README "Kernels &
roofline"):

- ``direct``       unique-build equi-join as a dense direct-address
                   table over the key domain: build = one scatter,
                   probe = one gather. Replaces sort+searchsorted when
                   host key bounds give a domain comparable to the
                   build cardinality (true for every NDS surrogate-key
                   dimension).
- ``matmul``       one-hot equality formulated as an f32 matmul so tiny
                   build sides (region/nation-class) probe on the MXU.
- ``partitioned``  M:N expanding join with on-device radix
                   partitioning: both sides scatter into R hash
                   partitions, per-partition sorts run BATCHED (one
                   ``lax.sort`` over an (R, cap) block sorts all
                   partitions at once at n/R sort depth), probes and
                   expansion stay per-partition. The q21-class
                   large-by-large answer.
- ``bitmask``      semi/anti joins as membership bitmaps (EXISTS) or
                   dense per-key min/max tables (EXISTS with the q21
                   ``<>`` residual) instead of gather joins.
- ``segscan``      grouped min/max as a segmented scan over the
                   already-sorted group ids + a gather at segment ends
                   (sum/count/avg were already scan-based): no scatter
                   anywhere in the grouped-aggregation path, and the
                   one group sort is amortized across every AggSpec of
                   the node.

The SELECTION is a planning-time decision: ``annotate`` walks a planned
tree and stamps ``node.kernel`` on Join/SemiJoin/Aggregate nodes from
the same catalog size statistics the scheduler's cost model uses
(``plan_verify.estimate_plan``). The choice is recorded IN the plan
(a dataclass field), so ``cache.fingerprint.canonical`` folds it into
the AOT fingerprint for free — two plans differing only in kernel
choice can never collide on one compiled program. The trace validates
feasibility at compile time (host bounds present, domain small enough)
and falls back to the sort path otherwise; the kernel actually USED is
counted per query and lands in the BenchReport ``kernels`` block, which
``ndsreport diff`` watches for silent demotions.

jax is imported lazily inside the device kernels: ``annotate`` and the
selection rules must stay importable on bare CPU (tools/ndsverify.py
plans and verifies the whole workload with no accelerator).
"""

from __future__ import annotations

import os

import numpy as np

from nds_tpu.sql import plan as P

# ------------------------------------------------------------ selection

# Join kernels (Join.kernel). "" = unannotated: legacy trace heuristics.
JOIN_SORT = "sortmerge"
JOIN_DIRECT = "direct"
JOIN_MATMUL = "matmul"
JOIN_PARTITIONED = "partitioned"
JOIN_KERNELS = ("", JOIN_SORT, JOIN_DIRECT, JOIN_MATMUL,
                JOIN_PARTITIONED)

# SemiJoin kernels
SEMI_SORT = "sortmerge"
SEMI_BITMASK = "bitmask"
SEMI_KERNELS = ("", SEMI_SORT, SEMI_BITMASK)

# Aggregate kernels
AGG_SEGSCAN = "segscan"
AGG_SCATTER = "scatter"
AGG_KERNELS = ("", AGG_SEGSCAN, AGG_SCATTER)

# builds at or below this many estimated rows probe via one-hot matmul
# (the equality matrix is (probe x build); 64 keeps it a thin MXU tile
# even against multi-million-row probes)
MATMUL_MAX_BUILD = 64
# largest dense direct-address table the trace will materialize
# (entries, not bytes: int32 -> 32 MiB at the cap)
DIRECT_MAX_DOMAIN = 1 << 23
# the dense table may be at most this many times larger than the build
# capacity — beyond it the scatter/gather wins are eaten by the
# table's own HBM traffic (surrogate keys are near-dense, ratio ~1-4)
DIRECT_DOMAIN_FACTOR = 16
# both sides of an M:N join must estimate at least this many rows for
# radix partitioning to beat one flat sort
PARTITION_MIN_ROWS = 1 << 16
# radix partition count (power of two; per-partition sort depth drops
# by log2(NPART) and all NPART sorts run as ONE batched lax.sort)
NPART = 8

ENV_FLAG = "NDS_TPU_KERNELS"


def kernels_enabled() -> bool:
    """Kill switch: NDS_TPU_KERNELS=0 leaves every plan unannotated so
    the legacy sort-based paths serve everything (A/B runs, ndsperf's
    "old" lane)."""
    return os.environ.get(ENV_FLAG, "1") not in ("0", "off")


# scan-filter selectivity guess per conjunct for the row estimator —
# only drives kernel thresholds, never correctness (the trace
# re-validates feasibility against real bounds at compile time)
_FILTER_SEL = 0.4


def _est_rows(node: P.Node, sizes: dict, memo: dict) -> float:
    """Planning-time row estimate per node, from the catalog's relative
    size statistics (the estimate_plan source the scheduler cost model
    already uses). Deterministic; coarse is fine — thresholds are
    order-of-magnitude decisions."""
    nid = id(node)
    if nid in memo:
        return memo[nid]
    # ndslint: waive[NDS101] -- memo lives for one annotate() pass over a live plan
    memo[nid] = 1.0  # cycle guard
    if isinstance(node, P.Scan):
        rows = float(sizes.get(node.table, 1000.0))
        rows *= _FILTER_SEL ** min(len(node.filters), 3)
    elif isinstance(node, P.Join):
        lr = _est_rows(node.left, sizes, memo)
        rr = _est_rows(node.right, sizes, memo)
        rows = lr if node.right_unique else max(lr, rr) * 2.0
        if node.kind in ("left", "full"):
            rows = lr + rr if node.kind == "full" else max(lr, rows)
    elif isinstance(node, P.SemiJoin):
        rows = _est_rows(node.left, sizes, memo)
    elif isinstance(node, P.SetOp):
        rows = (_est_rows(node.left, sizes, memo)
                + _est_rows(node.right, sizes, memo))
    elif isinstance(node, P.Aggregate):
        rows = _est_rows(node.child, sizes, memo)
    elif isinstance(node, P.Limit):
        rows = float(min(node.count,
                         _est_rows(node.child, sizes, memo)))
    elif isinstance(node, P.Filter):
        rows = _est_rows(node.child, sizes, memo) * _FILTER_SEL
    else:
        child = getattr(node, "child", None)
        rows = (_est_rows(child, sizes, memo)
                if isinstance(child, P.Node) else 1000.0)
    rows = max(rows, 1.0)
    # ndslint: waive[NDS101] -- memo lives for one annotate() pass over a live plan
    memo[nid] = rows
    return rows


def select_join_kernel(left_rows: float, right_rows: float,
                       right_unique: bool, kind: str) -> str:
    """The selection rule for one Join node (README documents it):
    unique builds go matmul (tiny) or direct (everything else — the
    trace demotes to sortmerge when bounds/domain disqualify); M:N
    inner joins go partitioned when both sides are large enough to
    amortize the radix scatter."""
    if right_unique:
        if right_rows <= MATMUL_MAX_BUILD:
            return JOIN_MATMUL
        return JOIN_DIRECT
    if (kind == "inner"
            and min(left_rows, right_rows) >= PARTITION_MIN_ROWS):
        return JOIN_PARTITIONED
    return JOIN_SORT


def annotate(planned, catalog=None) -> None:
    """Stamp a kernel choice on every Join/SemiJoin/Aggregate of a
    planned statement (in place; nodes already carrying an explicit
    choice are left alone). Called by the planner at the end of
    ``plan_statement``; a disabled env flag leaves plans untouched."""
    if not kernels_enabled():
        return
    if not isinstance(planned, P.PlannedQuery):
        return
    sizes = dict(getattr(catalog, "sizes", None) or {})
    memo: dict = {}
    for root in [planned.root, *planned.scalar_subplans]:
        if not isinstance(root, P.Node):
            continue
        for node in P.walk_plan(root):
            if isinstance(node, P.Join) and not node.kernel:
                node.kernel = select_join_kernel(
                    _est_rows(node.left, sizes, memo),
                    _est_rows(node.right, sizes, memo),
                    node.right_unique, node.kind)
            elif isinstance(node, P.SemiJoin) and not node.kernel:
                node.kernel = SEMI_BITMASK
            elif isinstance(node, P.Aggregate) and not node.kernel:
                node.kernel = AGG_SEGSCAN


def domain_of(lo, hi) -> "int | None":
    """Dense-table entry count for host key bounds, or None when the
    bounds are unusable (unknown, or too wide to enumerate)."""
    if lo is None or hi is None:
        return None
    dom = int(hi) - int(lo) + 1
    if dom < 1 or dom > DIRECT_MAX_DOMAIN:
        return None
    return dom


def direct_feasible(dom: "int | None", build_capacity: int) -> bool:
    """Whether a dense direct-address table of ``dom`` entries is worth
    building for a ``build_capacity``-slot build side (trace-time
    check; a False here demotes the node to the sort path and the
    demotion is visible in the per-query kernel counts)."""
    if dom is None:
        return False
    return dom <= max(build_capacity, 1) * DIRECT_DOMAIN_FACTOR


# -------------------------------------------------------- join kernels
#
# All device kernels import jax lazily (module docstring: annotate()
# must run accelerator-free) and are pure traced functions — no state,
# no host round trips; the caller owns capacity/overflow policy.

def direct_lookup_join(bkey, bok, pkey, pok, lo: int, dom: int):
    """Unique-build equi-join via a dense direct-address table.

    Build: scatter each valid build row's index at ``key - lo`` (unique
    keys guarantee no collision among valid rows). Probe: one gather.
    Returns ``(ridx, hit)`` with the same contract as the sort path's
    ``_probe`` — ``ridx`` clamped to a valid row wherever ``hit`` is
    False."""
    import jax.numpy as jnp
    n_build = bkey.shape[0]
    slots = (bkey.astype(jnp.int64) - lo).astype(jnp.int32)
    iota = jnp.arange(n_build, dtype=jnp.int32)
    tbl = jnp.full((dom,), -1, jnp.int32)
    # invalid build rows route to the out-of-range slot and drop
    tbl = tbl.at[jnp.where(bok, slots, dom)].set(iota, mode="drop")
    pos = pkey.astype(jnp.int64) - lo
    inb = (pos >= 0) & (pos < dom)
    ridx = jnp.take(tbl, jnp.clip(pos, 0, dom - 1).astype(jnp.int32))
    hit = pok & inb & (ridx >= 0)
    return jnp.maximum(ridx, 0), hit


def matmul_probe_join(bkey, bok, pkey, pok):
    """Unique-build equi-join as a one-hot matmul (TQP formulation):
    the (probe x build) equality matrix contracts against the build
    iota on the MXU. Build sides are capped tiny (MATMUL_MAX_BUILD), so
    the matrix is a thin tile against any probe length. f32 is exact
    for indices < 2^24, far above the cap."""
    import jax.numpy as jnp
    n_build = bkey.shape[0]
    eq = (pkey[:, None] == bkey[None, :]) & bok[None, :]
    eqf = eq.astype(jnp.float32)
    iota = jnp.arange(n_build, dtype=jnp.float32)
    ridx = jnp.dot(eqf, iota).astype(jnp.int32)
    hit = pok & (jnp.dot(eqf, jnp.ones((n_build,), jnp.float32)) > 0)
    return jnp.clip(ridx, 0, n_build - 1), hit


def bitmask_semi(bkey, bok, pkey, pok, lo: int, dom: int):
    """EXISTS / NOT EXISTS membership as a dense bitmap: build scatters
    True at each valid key slot, probe is one gather. Returns the
    per-probe-row ``exists`` mask (the caller negates for anti)."""
    import jax.numpy as jnp
    slots = (bkey.astype(jnp.int64) - lo).astype(jnp.int32)
    bm = jnp.zeros((dom,), bool)
    bm = bm.at[jnp.where(bok, slots, dom)].set(True, mode="drop")
    pos = pkey.astype(jnp.int64) - lo
    inb = (pos >= 0) & (pos < dom)
    member = jnp.take(bm, jnp.clip(pos, 0, dom - 1).astype(jnp.int32))
    return pok & inb & member


def keyed_minmax_semi(bkey, bok, bval, pkey, pok, pval, lo: int,
                      dom: int):
    """EXISTS with the q21 ``<>`` residual, dense formulation: exists a
    build row with this key and a DIFFERENT value  <=>  the per-key
    [min, max] of the build values is not exactly [pval, pval].
    Scatter-min/max into domain-sized tables replaces the 2-key
    whole-table sort + 2 searchsorteds of the sort path."""
    import jax.numpy as jnp
    slots = jnp.where(bok, (bkey.astype(jnp.int64) - lo), dom).astype(
        jnp.int32)
    vmax = jnp.iinfo(bval.dtype).max
    vmin = jnp.iinfo(bval.dtype).min
    mn = jnp.full((dom,), vmax, bval.dtype).at[slots].min(
        bval, mode="drop")
    mx = jnp.full((dom,), vmin, bval.dtype).at[slots].max(
        bval, mode="drop")
    present = jnp.zeros((dom,), bool).at[slots].set(True, mode="drop")
    pos = pkey.astype(jnp.int64) - lo
    inb = (pos >= 0) & (pos < dom)
    at = jnp.clip(pos, 0, dom - 1).astype(jnp.int32)
    has_key = pok & inb & jnp.take(present, at)
    differs = ((jnp.take(mn, at) != pval) | (jnp.take(mx, at) != pval))
    return has_key & differs


def _pids(key, log2r: int):
    """Radix partition id from the key's low 32 bits via a Knuth
    multiplicative hash — equal keys always co-locate, which is the
    only property partitioning needs."""
    import jax.numpy as jnp
    if log2r == 0:
        return jnp.zeros(key.shape, jnp.int32)
    u = key.astype(jnp.uint32) * jnp.uint32(2654435761)
    return (u >> jnp.uint32(32 - log2r)).astype(jnp.int32)


def _radix_scatter(key, ok, nparts: int, cap: int, log2r: int):
    """Scatter one side into (nparts, cap) partition blocks. Returns
    (keys, gidx, ok, overflow): per-slot key (sentinel-filled), source
    row index, occupancy, and the count of rows dropped because their
    partition overflowed ``cap`` (the caller's slack retry grows it)."""
    import jax.numpy as jnp
    n = key.shape[0]
    pid = _pids(key, log2r)
    oh = ((pid[:, None] == jnp.arange(nparts, dtype=jnp.int32)[None, :])
          & ok[:, None])
    ohi = oh.astype(jnp.int32)
    rank = jnp.take_along_axis(jnp.cumsum(ohi, axis=0),
                               pid[:, None], axis=1)[:, 0] - 1
    counts = jnp.sum(ohi, axis=0)
    okc = ok & (rank < cap)
    dest = jnp.where(okc, pid * cap + rank, nparts * cap)
    sent = jnp.iinfo(key.dtype).max
    keys = jnp.full((nparts * cap,), sent, key.dtype).at[dest].set(
        key, mode="drop")
    gidx = jnp.zeros((nparts * cap,), jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    occ = jnp.zeros((nparts * cap,), bool).at[dest].set(
        True, mode="drop")
    over = jnp.sum(jnp.maximum(counts - cap, 0))
    return (keys.reshape(nparts, cap), gidx.reshape(nparts, cap),
            occ.reshape(nparts, cap), over)


def partitioned_mn_join(lkey, lok, rkey, rok, out_capacity: int,
                        part_slack: float, nparts: int = NPART):
    """Radix-partitioned M:N expanding inner join.

    Both sides scatter into ``nparts`` hash partitions (equal keys
    co-locate), the build partitions sort as ONE batched ``lax.sort``
    over the (nparts, cap) block — per-partition sort depth is
    log(n/nparts), and the probe searchsorteds batch the same way —
    then the match-range expansion runs per partition at capacity
    ``out_capacity / nparts``. Returns ``(lidx, ridx, present,
    overflow)`` flattened to ``nparts * ceil(out_capacity / nparts)``
    slots; ``overflow`` counts both partition-capacity and
    output-capacity misses so the executor's doubled-slack retry
    (which grows ``part_slack`` and ``out_capacity`` together) covers
    skew the hash didn't balance."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    log2r = max(nparts.bit_length() - 1, 0)
    nl, nr = lkey.shape[0], rkey.shape[0]
    lcap = max(-(-int(nl * part_slack) // nparts), 1)
    rcap = max(-(-int(nr * part_slack) // nparts), 1)
    lk_p, lg_p, lok_p, lover = _radix_scatter(lkey, lok, nparts, lcap,
                                              log2r)
    rk_p, rg_p, rok_p, rover = _radix_scatter(rkey, rok, nparts, rcap,
                                              log2r)
    # batched per-partition build sort: sentinel-filled empty slots
    # sort to the tail exactly like _build_lookup's masked rows
    ks, gs = lax.sort([lk_p, lg_p], num_keys=1, is_stable=True)
    # ndslint: waive[NDS112] -- probe keys inherit the caller's width (narrowed by _join_key_arrays when bounds allow); wider packs need the 64-bit operand
    ss_l = jax.vmap(lambda a, q: jnp.searchsorted(a, q, side="left",
                                                  method="sort"))
    # ndslint: waive[NDS112] -- same operands as ss_l above
    ss_r = jax.vmap(lambda a, q: jnp.searchsorted(a, q, side="right",
                                                  method="sort"))
    lo_i = ss_l(ks, rk_p)
    hi_i = ss_r(ks, rk_p)
    # match counts accumulate in int64 like the legacy M:N path: a
    # skewed partition can expand past 2^31 pairs, and an int32 cumsum
    # wrap would corrupt present/offsets AND zero the overflow count,
    # defeating the doubled-slack retry. Only the clamped offsets
    # narrow to int32 (order-preserving for every slot < kp)
    cnt = jnp.where(rok_p, (hi_i - lo_i).astype(jnp.int64), 0)
    offs = jnp.cumsum(cnt, axis=1)
    total = offs[:, -1]
    kp = max(-(-out_capacity // nparts), 1)
    slots = jnp.arange(kp, dtype=jnp.int32)
    offsc = jnp.minimum(offs, kp + 1).astype(jnp.int32)
    # ndslint: waive[NDS112] -- both operands (offsc, slots) are int32 by construction two lines up
    rloc = jax.vmap(lambda o: jnp.searchsorted(o, slots, side="right",
                                               method="sort"))(offsc)
    rloc = jnp.clip(rloc, 0, rcap - 1)
    prev = jnp.where(rloc > 0,
                     jnp.take_along_axis(offsc,
                                         jnp.maximum(rloc - 1, 0),
                                         axis=1),
                     0)
    within = slots[None, :] - prev
    lpos = jnp.clip(jnp.take_along_axis(lo_i, rloc, axis=1) + within,
                    0, lcap - 1)
    lidx = jnp.take_along_axis(gs, lpos, axis=1)
    ridx = jnp.take_along_axis(rg_p, rloc, axis=1)
    present = slots[None, :] < jnp.minimum(total, kp)[:, None]
    overflow = (jnp.sum(jnp.maximum(total - kp, 0)).astype(jnp.int64)
                + lover.astype(jnp.int64) + rover.astype(jnp.int64))
    return (lidx.reshape(-1), ridx.reshape(-1), present.reshape(-1),
            overflow)


# ------------------------------------------------- aggregation kernels

def seg_scan(op, vals, flags):
    """Segmented inclusive scan: restart ``op`` accumulation at every
    True flag. Classic (value, reset-flag) associative combiner —
    O(n log n) on the VPU via ``lax.associative_scan``. (Moved here
    from device_exec so every segmented kernel shares one
    implementation.)"""
    from jax import lax

    def comb(a, b):
        av, af = a
        bv, bf = b
        import jax.numpy as jnp
        return jnp.where(bf, bv, op(av, bv)), af | bf

    out, _ = lax.associative_scan(comb, (vals, flags))
    return out


def seg_reduce_at_ends(op, data, gid, starts2):
    """Grouped reduction over SORTED group ids with no scatter: a
    segmented scan carries the running reduction, and each group's
    value is the scan at its last row (``starts2`` = first sorted row
    per group, n past the last group — the same array ``_seg_sum``
    differences its cumsum at). Rows outside any group must carry the
    op's identity in ``data``."""
    import jax.numpy as jnp
    n = data.shape[0]
    first = jnp.concatenate(
        [jnp.ones(1, bool), gid[1:] != gid[:-1]])
    run = seg_scan(op, data, first)
    nxt = jnp.concatenate(
        [starts2[1:], jnp.full((1,), n, starts2.dtype)])
    end = jnp.clip(nxt - 1, 0, n - 1)
    return jnp.take(run, end)


def part_reduce_broadcast(op, data, part_start, pend):
    """Per-row whole-partition reduction for window functions: the
    segmented scan's value at the partition's LAST row (``pend``,
    already per-row) broadcast back — replaces the ``segment_min/max``
    scatter + gather pair."""
    import jax.numpy as jnp
    run = seg_scan(op, data, part_start)
    return jnp.take(run, pend)


def last_of_group(change, n: int):
    """Index of the last row of each row's group, for sorted group
    ``change`` flags (True at each group's first row): a reversed
    running-min over future change positions, no scatter."""
    import jax.numpy as jnp
    from jax import lax
    iota = jnp.arange(n, dtype=jnp.int32)
    chg_at = jnp.where(change, iota, n)
    future = jnp.concatenate(
        [chg_at[1:], jnp.full((1,), n, jnp.int32)])
    nxt = lax.cummin(future, reverse=True)
    return jnp.clip(nxt - 1, 0, n - 1)


# ------------------------------------------------------ buffer donation

def donate_jit(fn, argnums):
    """``jax.jit`` with buffer donation for single-use inputs (the
    chunked phase-A chunk buffers; the result compactor's masked
    full-capacity arrays) so intermediate columns stop double-buffering
    (SNIPPETS [1]/[2] ``donate_argnums``). NDS_TPU_DONATE=0 disables.

    Donation only engages on accelerator backends: on CPU,
    ``jnp.asarray`` of a host numpy view is ZERO-COPY, so a donated
    input buffer can alias a live HostTable column and XLA's in-place
    reuse would scribble over the warehouse itself (observed: a
    donated chunk-scan corrupted ``sales`` for every later query of
    the process). On TPU/GPU the upload is always a device copy, the
    aliasing hazard cannot exist, and HBM residency is the thing worth
    halving. NDS_TPU_DONATE=force overrides for aliasing experiments.

    Donation is best-effort: jax warns (and keeps both buffers) when an
    input is not donatable — e.g. two pytree leaves aliasing one
    buffer — which is noise here, not a defect, so the warning is
    filtered at call sites via ``silence_donation_warnings``."""
    import jax
    if not donate_enabled():
        # ndslint: waive[NDS111] -- builds the traced callable only; lower+compile stays inside cache.aot at the call sites
        return jax.jit(fn)
    # ndslint: waive[NDS111] -- builds the traced callable only; lower+compile stays inside cache.aot at the call sites
    return jax.jit(fn, donate_argnums=argnums)


def donate_enabled() -> bool:
    """The donation decision ``donate_jit`` applies, exported so the
    chunk-scan AOT fingerprint can fold the ACTUAL choice in (a blob
    compiled with donation must not serve a process that decided
    against it, and vice versa)."""
    import jax
    mode = os.environ.get("NDS_TPU_DONATE", "1")
    if mode in ("0", "off"):
        return False
    if mode == "force":
        return True
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 - backend probe must not fail a build
        return False


def silence_donation_warnings():
    """Filter jax's "Some donated buffers were not usable" UserWarning
    once per process: a non-donatable buffer silently keeps the old
    double-buffered behavior, which is the correct degradation."""
    import warnings
    global _DONATION_WARNINGS_SILENCED
    if _DONATION_WARNINGS_SILENCED:
        return
    _DONATION_WARNINGS_SILENCED = True
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable")


_DONATION_WARNINGS_SILENCED = False
