"""Query session: the engine-side analog of a SparkSession.

Holds the table registry (reference: temp views created per table,
`nds/nds_power.py:79-106`), session views (q15), parses + plans + executes
SQL, and exposes the executor backend choice (CPU oracle vs device
engine) the way templates choose cpu/gpu in the reference.
"""

from __future__ import annotations

from nds_tpu.engine.cpu_exec import CpuExecutor, ResultTable
from nds_tpu.io.host_table import HostTable
from nds_tpu.sql import plan as P
from nds_tpu.sql.parser import parse
from nds_tpu.sql.planner import CatalogInfo, Planner

# relative size weights for greedy join ordering (TPC-H row ratios)
TPCH_SIZES = {
    "lineitem": 6_000_000, "orders": 1_500_000, "partsupp": 800_000,
    "part": 200_000, "customer": 150_000, "supplier": 10_000,
    "nation": 25, "region": 5,
}


class Session:
    # bound on the (SQL text, views) -> plan cache: a serving workload
    # submits an unbounded population of literal-variant texts, and an
    # unbounded dict would leak plans for the process lifetime
    PLAN_CACHE_MAX = 512

    def __init__(self, catalog: CatalogInfo, executor_factory=None,
                 parameterize: "bool | None" = None):
        self.catalog = catalog
        self.tables: dict[str, HostTable] = {}
        self.views: dict[str, P.Node] = {}
        # literal hoisting (sql/params.py): default from
        # NDS_TPU_PARAM_PLANS; the serving layer turns it on explicitly
        from nds_tpu.sql import params as sqlparams
        self.parameterize = (sqlparams.enabled_by_env()
                             if parameterize is None else parameterize)
        self._executor_factory = executor_factory or (
            # ndslint: waive[NDS110] -- bare sessions default to the CPU oracle directly; the pipeline only schedules engine-backed placements (make_session routes every backend through it)
            lambda tables: CpuExecutor(tables))
        # plan cache keyed by (SQL text, view-definition signature):
        # repeated queries (warmup passes, throughput streams) reuse the
        # SAME plan object, which is also the device engine's compile-cache
        # key — the load-once/query-many lifecycle of
        # `nds/nds_power.py:184-322`. The signature is the set of
        # (view name, view source SQL) currently defined, so q15's
        # CREATE/DROP VIEW cycle maps every pass onto one cache entry
        # (identical view body => identical signature => no replan and no
        # XLA recompile), while a re-created view with a DIFFERENT body
        # changes the signature and correctly replans.
        self._plan_cache: dict[tuple, object] = {}
        self._view_sql: dict[str, str] = {}

    @classmethod
    def for_nds_h(cls, executor_factory=None,
                  parameterize: "bool | None" = None) -> "Session":
        from nds_tpu.nds_h.schema import PRIMARY_KEYS, get_schemas
        cat = CatalogInfo(get_schemas(), PRIMARY_KEYS, dict(TPCH_SIZES))
        return cls(cat, executor_factory, parameterize=parameterize)

    @classmethod
    def for_nds(cls, executor_factory=None,
                use_decimal: bool = True,
                include_maintenance: bool = False,
                parameterize: "bool | None" = None) -> "Session":
        from nds_tpu.nds.schema import (
            PRIMARY_KEYS, SIZES, get_maintenance_schemas, get_schemas,
        )
        schemas = get_schemas(use_decimal)
        keys = dict(PRIMARY_KEYS)
        sizes = dict(SIZES)
        if include_maintenance:
            # the 12 s_*/delete staging tables the LF_*/DF_* refresh
            # functions read (`nds/nds_maintenance.py:270-274` registers
            # them as temp views)
            schemas = {**schemas, **get_maintenance_schemas(use_decimal)}
            keys.update({"s_purchase": ("purc_purchase_id",),
                         "s_catalog_order": ("cord_order_id",),
                         "s_web_order": ("word_order_id",)})
            sizes.update({t: 100.0 for t in
                          get_maintenance_schemas(use_decimal)})
        cat = CatalogInfo(schemas, keys, sizes)
        return cls(cat, executor_factory, parameterize=parameterize)

    def register_table(self, table: HostTable) -> None:
        self.tables[table.name] = table

    def plan(self, sql_text: str):
        from nds_tpu.obs.trace import get_tracer
        from nds_tpu.resilience import faults
        # chaos site: deterministic plan-time faults must fail fast
        # (the retry classifier never retries this class)
        faults.fault_point("plan")
        with get_tracer().span("sql.parse", chars=len(sql_text)):
            stmt = parse(sql_text)
        return self.plan_ast(stmt)

    def plan_ast(self, stmt):
        planner = Planner(self.catalog, self.views,
                          parameterize=self.parameterize)
        planned = planner.plan_statement(stmt)
        from nds_tpu.analysis import plan_verify
        if plan_verify.verify_enabled():
            # NDS_TPU_VERIFY_PLANS=1 (always on in tests): reject a
            # structurally invalid plan here, where the statement text
            # is known, instead of as a KeyError inside an executor
            target = planned[2] if isinstance(planned, tuple) else planned
            if isinstance(target, P.PlannedQuery):
                plan_verify.assert_valid(target, catalog=self.catalog,
                                         label=type(stmt).__name__)
        return planned

    def _views_signature(self) -> frozenset:
        return frozenset(self._view_sql.items())

    def invalidate(self, tables=None) -> None:
        """Drop content-derived caches after a table mutation. With
        ``tables=None`` everything goes (the pre-delta behavior, still
        right for wholesale warehouse swaps like rollback). With a
        table-name iterable, eviction is SCOPED: only plan-cache
        entries whose plans scan a mutated table are dropped, and the
        executor factory is asked for a scoped invalidate — segment-
        granular content digests guarantee unaffected programs stay
        correct, so unaffected queries re-run at 0 compiles."""
        if tables is None:
            self._plan_cache.clear()
            inv = getattr(self._executor_factory, "invalidate", None)
            if inv is not None:
                inv()
            return
        touched = set(tables)
        from nds_tpu.cache import fingerprint
        for key in [k for k, planned in self._plan_cache.items()
                    if not isinstance(planned, tuple)
                    and touched.intersection(
                        fingerprint.scan_tables(planned))]:
            self._plan_cache.pop(key, None)
        inv_scoped = getattr(self._executor_factory,
                             "invalidate_tables", None)
        if inv_scoped is not None:
            inv_scoped(touched)
        else:
            inv = getattr(self._executor_factory, "invalidate", None)
            if inv is not None:
                inv()

    def _run_dml(self, action: str, name: str, payload) -> None:
        from nds_tpu.engine import dml
        table = self.tables.get(name)
        if table is None:
            raise ValueError(f"DML target {name!r} is not registered")
        if action == "insert":
            executor = self._executor_factory(self.tables)
            result = executor.execute(payload)
            self.tables[name] = dml.append_rows(table, result)
        else:  # delete
            keep = dml.delete_mask(self, table, payload)
            self.tables[name] = dml.apply_delete(table, keep)
        self.invalidate(tables=[name])

    def _planned_for(self, key: tuple, sql_text: str):
        """Plan-cache lookup that keeps the 'plan' chaos site firing
        exactly once per query submission: a cache MISS fires inside
        plan(); a HIT fires here (warmup passes populate the cache —
        a scheduled plan fault must still reach the timed pass)."""
        planned = self._plan_cache.get(key)
        if planned is None:
            planned = self.plan(sql_text)
            self._plan_cache[key] = planned
            while len(self._plan_cache) > self.PLAN_CACHE_MAX:
                # FIFO bound: a serving workload's literal-variant texts
                # must not grow the plan cache for the process lifetime
                # (the shared COMPILED program lives in the executor's
                # digest-keyed cache, not here)
                self._plan_cache.pop(next(iter(self._plan_cache)))
        else:
            from nds_tpu.resilience import faults
            faults.fault_point("plan")
        return planned

    def sql(self, sql_text: str) -> ResultTable | None:
        key = (sql_text, self._views_signature())
        planned = self._planned_for(key, sql_text)
        return self._run_planned(key, sql_text, planned)

    def _run_planned(self, key: tuple, sql_text: str, planned):
        if isinstance(planned, tuple):
            action, name, node = planned
            if action == "create_view":
                if name in self.views:
                    raise ValueError(f"view {name!r} already exists")
                self.views[name] = node
                self._view_sql[name] = sql_text
                return None
            if action == "drop_view":
                if name not in self.views and node != "if_exists":
                    raise ValueError(f"view {name!r} does not exist")
                self.views.pop(name, None)
                self._view_sql.pop(name, None)
                return None
            if action in ("insert", "delete"):
                # never replay a stale DML plan against mutated tables
                self._plan_cache.pop(key, None)
                self._run_dml(action, name, node)
                return None
        executor = self._executor_factory(self.tables)
        return executor.execute(planned)

    def sql_async(self, sql_text: str):
        """Dispatch-without-wait variant of sql(): returns a handle with
        .result(). SELECTs on executors supporting execute_async (the
        device engine) overlap with the caller's other work
        (`engine.concurrent_tasks` pipelining); everything else runs
        synchronously and returns an already-completed handle."""
        key = (sql_text, self._views_signature())
        planned = self._planned_for(key, sql_text)
        if not isinstance(planned, tuple):
            executor = self._executor_factory(self.tables)
            dispatch = getattr(executor, "execute_async", None)
            if dispatch is not None:
                return dispatch(planned)
        return _Completed(self._run_planned(key, sql_text, planned))


class _Completed:
    """Already-finished async handle (CPU oracle, DML, view DDL)."""

    def __init__(self, value):
        self._value = value

    def result(self):
        return self._value
