"""Query session: the engine-side analog of a SparkSession.

Holds the table registry (reference: temp views created per table,
`nds/nds_power.py:79-106`), session views (q15), parses + plans + executes
SQL, and exposes the executor backend choice (CPU oracle vs device
engine) the way templates choose cpu/gpu in the reference.
"""

from __future__ import annotations

from nds_tpu.engine.cpu_exec import CpuExecutor, ResultTable
from nds_tpu.io.host_table import HostTable
from nds_tpu.sql import plan as P
from nds_tpu.sql.parser import parse
from nds_tpu.sql.planner import CatalogInfo, Planner

# relative size weights for greedy join ordering (TPC-H row ratios)
TPCH_SIZES = {
    "lineitem": 6_000_000, "orders": 1_500_000, "partsupp": 800_000,
    "part": 200_000, "customer": 150_000, "supplier": 10_000,
    "nation": 25, "region": 5,
}


class Session:
    def __init__(self, catalog: CatalogInfo, executor_factory=None):
        self.catalog = catalog
        self.tables: dict[str, HostTable] = {}
        self.views: dict[str, P.Node] = {}
        self._executor_factory = executor_factory or (
            lambda tables: CpuExecutor(tables))
        # plan cache keyed by (SQL text, view-definition signature):
        # repeated queries (warmup passes, throughput streams) reuse the
        # SAME plan object, which is also the device engine's compile-cache
        # key — the load-once/query-many lifecycle of
        # `nds/nds_power.py:184-322`. The signature is the set of
        # (view name, view source SQL) currently defined, so q15's
        # CREATE/DROP VIEW cycle maps every pass onto one cache entry
        # (identical view body => identical signature => no replan and no
        # XLA recompile), while a re-created view with a DIFFERENT body
        # changes the signature and correctly replans.
        self._plan_cache: dict[tuple, object] = {}
        self._view_sql: dict[str, str] = {}

    @classmethod
    def for_nds_h(cls, executor_factory=None) -> "Session":
        from nds_tpu.nds_h.schema import PRIMARY_KEYS, get_schemas
        cat = CatalogInfo(get_schemas(), PRIMARY_KEYS, dict(TPCH_SIZES))
        return cls(cat, executor_factory)

    @classmethod
    def for_nds(cls, executor_factory=None,
                use_decimal: bool = True) -> "Session":
        from nds_tpu.nds.schema import PRIMARY_KEYS, SIZES, get_schemas
        cat = CatalogInfo(get_schemas(use_decimal), PRIMARY_KEYS,
                          dict(SIZES))
        return cls(cat, executor_factory)

    def register_table(self, table: HostTable) -> None:
        self.tables[table.name] = table

    def plan(self, sql_text: str):
        planner = Planner(self.catalog, self.views)
        return planner.plan_statement(parse(sql_text))

    def _views_signature(self) -> frozenset:
        return frozenset(self._view_sql.items())

    def sql(self, sql_text: str) -> ResultTable | None:
        key = (sql_text, self._views_signature())
        planned = self._plan_cache.get(key)
        if planned is None:
            planned = self.plan(sql_text)
            self._plan_cache[key] = planned
        if isinstance(planned, tuple):
            action, name, node = planned
            if action == "create_view":
                if name in self.views:
                    raise ValueError(f"view {name!r} already exists")
                self.views[name] = node
                self._view_sql[name] = sql_text
                return None
            if action == "drop_view":
                self.views.pop(name, None)
                self._view_sql.pop(name, None)
                return None
        executor = self._executor_factory(self.tables)
        return executor.execute(planned)
