"""Chunked (out-of-core) execution: tables larger than HBM stream
through the device in fixed-size chunks.

SURVEY.md §7 hard part 4: at SF3K a fact table (and its shuffle) exceeds
HBM, and the reference gets spill for free from Spark's block shuffle
(SURVEY.md §2.6). The TPU-native answer here is HOST-STAGED execution:

- big tables live in host RAM only; the device never holds more than
  ``chunk_rows`` of them at once;
- phase A (streaming scan): one compiled chunk program per streamed
  table evaluates every pushed-down scan filter for that table
  (`plan.Scan.filters`) over each chunk and returns just a keep-bitmap
  — values never round-trip; the host gathers surviving rows into a
  reduced table. Filters are re-applied in phase B, so phase A may be
  conservative (any filter it cannot evaluate keeps all rows);
- phase B: the UNCHANGED plan executes against the reduced table with
  the normal static-shape engine — now sized by post-filter survivors,
  not raw rows.

This bounds device residency by max(chunk, survivors): the engine runs
any query whose post-filter working set fits HBM, regardless of raw
table size. (The follow-on stage for full-scan aggregations — partial
aggregation per chunk with host combine — composes with the same chunk
loop.)

The per-chunk program is compiled ONCE per (table, plan): every chunk
has the same static shape; the tail chunk passes its logical row count
as a traced scalar, not a new shape.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from nds_tpu.engine import device_exec as dx
from nds_tpu.engine.device_exec import DCtx, DVal
from nds_tpu.io.host_table import HostColumn, HostTable
from nds_tpu.sql import plan as P

# stream tables above this many bytes (column data, host-side estimate);
# the default targets a 16G-HBM chip with headroom for join expansion
DEFAULT_STREAM_BYTES = 2 << 30
DEFAULT_CHUNK_ROWS = 1 << 20


def _table_bytes(t: HostTable) -> int:
    total = 0
    for c in t.columns.values():
        total += c.values.nbytes
        if c.null_mask is not None:
            total += c.null_mask.nbytes
    return total


class _PhaseBExecutor(dx.DeviceExecutor):
    """Per-plan executor over {full tables, streamed->reduced}: device
    buffers for NON-streamed tables come from a pool shared across every
    phase-B executor (dimension columns upload once per session, the
    load-once/query-many lifecycle), while reduced-table buffers stay
    local — their contents differ per plan."""

    def __init__(self, tables, float_dtype, shared_buffers: dict,
                 streamed: set):
        super().__init__(tables, float_dtype)
        self._shared = shared_buffers
        self._streamed = streamed

    def _upload(self, bufs: dict, table: str, name: str) -> None:
        pool = (self._buffers if table in self._streamed
                else self._shared)
        key = f"{table}.{name}"
        if key not in pool:
            col = self.tables[table].columns[name]
            pool[key] = jnp.asarray(col.values)
            if col.null_mask is not None:
                pool[key + "#v"] = jnp.asarray(col.null_mask)
        bufs[key] = pool[key]
        if key + "#v" in pool:
            bufs[key + "#v"] = pool[key + "#v"]


class ChunkedExecutor(dx.DeviceExecutor):
    """DeviceExecutor that streams oversized tables through the chip."""

    # phase-B executors kept alive (compiled programs + reduced
    # buffers); older ones evict so reduced-row HBM doesn't accumulate
    # across a 99-query power run
    MAX_REDUCED = 16

    def __init__(self, tables: dict[str, HostTable],
                 stream_bytes: int = DEFAULT_STREAM_BYTES,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS,
                 float_dtype=None):
        super().__init__(tables, float_dtype)
        self.stream_bytes = stream_bytes
        self.chunk_rows = chunk_rows
        # (plan key) -> phase-B executor
        self._reduced: dict[object, _PhaseBExecutor] = {}
        # (table, filter repr) -> reduced HostTable, shared across plans
        self._survivor_cache: dict[tuple, HostTable] = {}

    def _is_streamed(self, table: str) -> bool:
        return _table_bytes(self.tables[table]) > self.stream_bytes

    # ----------------------------------------------------------------- API

    def execute_async(self, planned: P.PlannedQuery, key: object = None):
        key = key if key is not None else id(planned)
        scans = self._streamed_scans(planned)
        if not scans:
            return super().execute_async(planned, key)
        if key not in self._reduced:
            reduced = {}
            for table, table_scans in scans.items():
                reduced[table] = self._reduce_table(table, table_scans)
            sub = _PhaseBExecutor({**self.tables, **reduced},
                                  self.float_dtype, self._buffers,
                                  set(reduced))
            while len(self._reduced) >= self.MAX_REDUCED:
                self._reduced.pop(next(iter(self._reduced)))
            self._reduced[key] = sub
        sub = self._reduced[key]
        res = sub.execute_async(planned, key)
        self.last_timings = sub.last_timings
        return res

    def _streamed_scans(self, planned: P.PlannedQuery) -> dict:
        """{table: [Scan, ...]} for streamed tables in this plan."""
        out: dict[str, list] = {}
        for root in [planned.root] + list(planned.scalar_subplans):
            for node in P.walk_plan(root):
                if isinstance(node, P.Scan) and self._is_streamed(
                        node.table):
                    out.setdefault(node.table, []).append(node)
        return out

    # ------------------------------------------------- phase A: chunk scan

    def _reduce_table(self, table: str, scans: list) -> HostTable:
        t = self.tables[table]
        # one reduced table serves every scan of it in the plan: a row
        # survives if ANY scan's filter conjunction accepts it (each
        # scan re-applies its own filters in phase B)
        cache_key = (table, tuple(sorted(
            repr(s.filters) for s in scans)))
        hit = self._survivor_cache.get(cache_key)
        if hit is not None:
            return hit
        need_cols = sorted({name for s in scans for name, _ in s.output})
        keep = self._chunk_keep_mask(table, scans, need_cols)
        if keep.all():
            # zero reduction (filterless scan / fallback): the original
            # table IS the result — no multi-GB host copy
            reduced = t
        else:
            idx = np.nonzero(keep)[0]
            cols = {}
            for name in t.columns:
                c = t.columns[name]
                cols[name] = HostColumn(
                    c.dtype, c.values[idx], c.dictionary,
                    None if c.null_mask is None else c.null_mask[idx])
            reduced = HostTable(table, t.schema, cols)
        # bounded like _reduced: host RAM for survivor copies must not
        # accumulate across a 99-query run (live phase-B executors keep
        # their own references; eviction only drops the shared entry)
        while len(self._survivor_cache) >= self.MAX_REDUCED:
            self._survivor_cache.pop(next(iter(self._survivor_cache)))
        self._survivor_cache[cache_key] = reduced
        return reduced

    def _chunk_keep_mask(self, table: str, scans: list,
                         need_cols: list) -> np.ndarray:
        t = self.tables[table]
        n = t.nrows
        C = min(self.chunk_rows, max(n, 1))
        # an EMPTY filter conjunction accepts every row: if any scan of
        # this table is filterless, no reduction is possible (the one
        # reduced table serves all scans of it in phase B)
        if any(not s.filters for s in scans):
            return np.ones(n, dtype=bool)
        live_scans = scans

        skipped: list = []

        def fn(bufs, n_valid):
            base = jnp.arange(C, dtype=jnp.int32) < n_valid
            keep = jnp.zeros(C, dtype=bool)
            for scan in live_scans:
                tr = dx._Trace(self, bufs)
                ctx = DCtx(C, base)
                for name, _dt in scan.output:
                    col = t.columns[name]
                    lo, hi = self.col_bounds(table, name)
                    sdict = col.dictionary if col.is_string else None
                    ctx.cols[(scan.binding, name)] = DVal(
                        bufs[name], bufs.get(name + "#v"), sdict, lo, hi)
                for pred in scan.filters:
                    # PER-PREDICATE fallback: a filter the chunk
                    # program cannot evaluate (e.g. it references a
                    # scalar-subquery result, q32/q92 shape) is simply
                    # skipped — the other predicates (date ranges!)
                    # still reduce, and phase B re-applies everything
                    try:
                        ctx = tr._apply_filter(ctx, pred)
                    except Exception as exc:  # noqa: BLE001
                        skipped.append((pred, exc))
                keep = keep | ctx.row
            return keep

        try:
            jitted = jax.jit(fn)
            keep_np = np.empty(n, dtype=bool)
            for start in range(0, n, C):
                stop = min(start + C, n)
                bufs = {}
                for name in need_cols:
                    col = t.columns[name]
                    sl = col.values[start:stop]
                    m = (None if col.null_mask is None
                         else col.null_mask[start:stop])
                    if stop - start < C:  # tail: pad to the chunk shape
                        pad = C - (stop - start)
                        sl = np.concatenate(
                            [sl, np.zeros(pad, dtype=sl.dtype)])
                        if m is not None:
                            m = np.concatenate(
                                [m, np.zeros(pad, dtype=bool)])
                    bufs[name] = jnp.asarray(sl)
                    if m is not None:
                        bufs[name + "#v"] = jnp.asarray(m)
                keep_np[start:stop] = np.asarray(
                    jitted(bufs, jnp.int32(stop - start)))[:stop - start]
            if skipped:
                from nds_tpu.utils.report import TaskFailureCollector
                TaskFailureCollector.notify(
                    f"chunked scan of {table}: {len(skipped)} filter(s) "
                    f"not chunk-evaluable, re-applied in phase B only "
                    f"({type(skipped[0][1]).__name__})")
            return keep_np
        except Exception as exc:  # noqa: BLE001 - conservative fallback
            from nds_tpu.utils.report import TaskFailureCollector
            TaskFailureCollector.notify(
                f"chunked scan fell back to full rows for {table}: "
                f"{type(exc).__name__}: {exc}")
            return np.ones(n, dtype=bool)


def make_chunked_factory(stream_bytes: int = DEFAULT_STREAM_BYTES,
                         chunk_rows: int = DEFAULT_CHUNK_ROWS,
                         precision: str = "f64"):
    """Session executor factory (make_device_factory analog) for the
    out-of-core engine."""
    if precision not in dx.PRECISIONS:
        raise ValueError(f"unknown engine.precision {precision!r}")
    name = dx.PRECISIONS[precision]
    float_dtype = None if name is None else getattr(jnp, name)
    holder: dict = {}

    def factory(tables):
        ex = holder.get("ex")
        if ex is None or ex.tables is not tables:
            ex = ChunkedExecutor(tables, stream_bytes, chunk_rows,
                                 float_dtype)
            holder["ex"] = ex
        return ex

    factory.invalidate = holder.clear
    return factory
