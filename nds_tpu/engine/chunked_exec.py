"""Chunked (out-of-core) execution: tables larger than HBM stream
through the device in fixed-size chunks.

SURVEY.md §7 hard part 4: at SF3K a fact table (and its shuffle) exceeds
HBM, and the reference gets spill for free from Spark's block shuffle
(SURVEY.md §2.6). The TPU-native answer here is HOST-STAGED execution:

- big tables live in host RAM only; the device never holds more than
  ``chunk_rows`` of them at once;
- phase A (streaming scan): one compiled chunk program per streamed
  table evaluates every pushed-down scan filter for that table
  (`plan.Scan.filters`) over each chunk and returns just a keep-bitmap
  — values never round-trip; the host gathers surviving rows into a
  reduced table. Filters are re-applied in phase B, so phase A may be
  conservative (any filter it cannot evaluate keeps all rows);
- phase B: the UNCHANGED plan executes against the reduced table with
  the normal static-shape engine — now sized by post-filter survivors,
  not raw rows.

This bounds device residency by max(chunk, survivors): the engine runs
any query whose post-filter working set fits HBM, regardless of raw
table size. (The follow-on stage for full-scan aggregations — partial
aggregation per chunk with host combine — composes with the same chunk
loop.)

The per-chunk program is compiled ONCE per (table, plan): every chunk
has the same static shape; the tail chunk passes its logical row count
as a traced scalar, not a new shape.

Both phase-A loops ride the double-buffered prefetcher
(``engine/pipeline_io.py``, README "Pipelined execution"): host-side
slicing + columnar encoding + the ``jax.device_put`` for chunk N+1 run
on a worker thread while the compiled program scans chunk N, so scan
never blocks compute. ``engine.prefetch.enabled=off`` /
``NDS_TPU_PREFETCH=0`` restores the byte-identical serial loops; the
prefetch shapes nothing the chunkscan fingerprint sees, so warm-cache
runs stay at zero compiles either way.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from nds_tpu.analysis import jitsan
from nds_tpu.engine import device_exec as dx
from nds_tpu.engine import pipeline_io
from nds_tpu.engine.device_exec import DCtx, DVal
from nds_tpu.engine.types import (
    INT64, DecimalType, FloatType, Schema, StringType,
)
from nds_tpu.io.host_table import HostColumn, HostTable, encode_strings
from nds_tpu.obs import costs as obs_costs
from nds_tpu.obs import memwatch
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.obs.trace import get_tracer
from nds_tpu.resilience import watchdog
from nds_tpu.resilience.retry import (
    QueryDeadlineExceeded, check_deadline, is_oom,
)
from nds_tpu.sql import ir
from nds_tpu.sql import plan as P

# stream tables above this many bytes (column data, host-side estimate);
# the default targets a 16G-HBM chip with headroom for join expansion
DEFAULT_STREAM_BYTES = 2 << 30
DEFAULT_CHUNK_ROWS = 1 << 20


def _table_bytes(t: HostTable) -> int:
    """Raw host bytes of a table — the stream/upload decision input.
    Deliberately NOT the encoded size: streaming is about host->device
    transfer and residency headroom for the UNREDUCED table, and a
    table that only fits encoded should still take the chunked path's
    conservative route (the governor's budget math is where encoded
    widths apply — analysis/plan_verify._scan_bytes)."""
    return sum(c.nbytes for c in t.columns.values())


class _PhaseBExecutor(dx.DeviceExecutor):
    """Per-plan executor over {full tables, streamed->reduced}: device
    buffers for NON-streamed tables come from a pool shared across every
    phase-B executor (dimension columns upload once per session, the
    load-once/query-many lifecycle), while reduced-table buffers stay
    local — their contents differ per plan."""

    # tables here are already survivor-reduced by the union of the
    # plan's scan filters; a second per-scan shrink would desync
    # _PartialAggExecutor's buffer walk from the trace for marginal gain
    SCAN_REDUCE = False

    def __init__(self, tables, float_dtype, shared_buffers: dict,
                 streamed: set):
        super().__init__(tables, float_dtype)
        self._shared = shared_buffers
        self._streamed = streamed

    def _upload(self, bufs: dict, table: str, name: str) -> None:
        pool = (self._buffers if table in self._streamed
                else self._shared)
        # the shared pool-placement helper also applies the columnar
        # encoding (nds_tpu/columnar/): dimension columns upload
        # encoded ONCE into the shared pool, reduced streamed tables
        # encode into the executor-local pool per plan
        self._pool_upload(pool, bufs, table, name)


def _walk_skip(node: P.Node, skip: set):
    """walk_plan that does not descend below replaced nodes."""
    yield node
    if id(node) in skip:
        return
    for c in P.children(node):
        yield from _walk_skip(c, skip)


class _MergeTrace(dx._Trace):
    """Trace that substitutes a chunk-partial MERGE for one Aggregate
    node: when execution reaches the original aggregate, it instead
    aggregates the concatenated per-chunk partials (already in the
    buffer set) and re-maps the merged columns onto the original
    binding — sum of sums, sum of counts, min of mins, and
    sum/count recomposition for avg."""

    def run(self, node: P.Node) -> DCtx:
        rep = getattr(self.ex, "_replace", None)
        if rep and id(node) in rep and id(node) not in self._cache:
            self.stash(node, self._merged_ctx(*rep[id(node)]))
        return super().run(node)

    def _merged_ctx(self, merge_node: P.Aggregate,
                    A: P.Aggregate, sum_dtypes: dict) -> DCtx:
        mctx = self.run(merge_node)
        mb = merge_node.binding
        out = DCtx(mctx.n, mctx.row)
        for n, _e in A.group_keys:
            out.cols[(A.binding, n)] = mctx.cols[(mb, n)]
        for n, spec in A.aggs:
            if spec.func == "avg":
                s = mctx.cols[(mb, n + "__s")]
                c = mctx.cols[(mb, n + "__c")]
                f = dx._to_float(s.arr, sum_dtypes[n], self.fdt)
                cnt = c.arr.astype(self.fdt)
                arr = f / jnp.maximum(cnt, 1)
                valid = c.arr > 0
                if s.valid is not None:
                    valid = valid & s.valid
                out.cols[(A.binding, n)] = DVal(arr, valid)
            else:
                out.cols[(A.binding, n)] = mctx.cols[(mb, n)]
        return out


class _PartialAggExecutor(_PhaseBExecutor):
    """Phase-B executor for the partial-aggregation path: executes the
    ORIGINAL plan, but the subtree under the split Aggregate never runs
    (its buffers are never uploaded) — the merge plan over the partials
    table stands in for it via _MergeTrace. Non-streamed buffers come
    from the shared pool (_PhaseBExecutor contract)."""

    def __init__(self, tables, float_dtype, shared_buffers, streamed,
                 replace: dict, extra_roots: list):
        super().__init__(tables, float_dtype, shared_buffers, streamed)
        self._replace = replace
        self._extra_roots = extra_roots

    def _collect_buffers(self, planned: P.PlannedQuery) -> dict:
        bufs = {}
        roots = ([planned.root] + list(planned.scalar_subplans)
                 + self._extra_roots)
        for root in roots:
            for node in _walk_skip(root, set(self._replace)):
                if isinstance(node, P.Scan):
                    for name, _dt in node.output:
                        self._upload(bufs, node.table, name)
        return bufs

    def _compile(self, planned: P.PlannedQuery,
                 slack: float = dx.DeviceExecutor.DEFAULT_SLACK):
        side = {}

        def fn(bufs):
            tr = _MergeTrace(self, bufs, slack)
            row, outs, dicts = tr.run_query(planned)
            side["dicts"] = dicts
            return row, outs, tr.total_overflow()

        # ndslint: waive[NDS111] -- builds the traced callable only; AOT lower+compile routes through cache.aot (_compile_or_load)
        return jax.jit(fn), side

    def _fingerprint_roots(self) -> list:
        """The merge substitution shapes the program but lives OUTSIDE
        the PlannedQuery (the trace swaps it in at id-matched nodes):
        fold the merge plans into the fingerprint or a plain phase-B
        program of the same plan would key-collide. The partials
        table's content stamp rides along via the merge plan's scan."""
        return list(self._extra_roots)


class _ForwardResult:
    """Async handle that forwards the phase-B sub-executor's finalized
    timings + query span back onto the outer ChunkedExecutor when the
    caller blocks on result(). Phase A's prefetch attribution
    (engine/pipeline_io.py) merges into the published timings here —
    the one place the sub-executor's bill and the outer executor's
    staging overlap meet."""

    __slots__ = ("outer", "sub", "inner", "pf")

    def __init__(self, outer, sub, inner, pf=None):
        self.outer = outer
        self.sub = sub
        self.inner = inner
        self.pf = dict(pf or {})

    def result(self):
        out = self.inner.result()
        timings = self.sub.last_timings
        span = getattr(self.sub, "last_query_span", None)
        if self.pf and isinstance(timings, dict):
            timings.update(self.pf)
            # the span carries a FILTERED copy of the timings as its
            # exported attr (device_exec._finish_traced): update it too
            # so span-fed consumers (obs.query_timings) see the
            # prefetch keys
            attr = span.attrs.get("timings") if span else None
            if isinstance(attr, dict):
                attr.update(self.pf)
        self.outer.last_timings = timings
        self.outer.last_query_span = span
        return out


class ChunkedExecutor(dx.DeviceExecutor):
    """DeviceExecutor that streams oversized tables through the chip."""

    # phase-B executors kept alive (compiled programs + reduced
    # buffers); older ones evict so reduced-row HBM doesn't accumulate
    # across a 99-query power run
    MAX_REDUCED = 16

    def __init__(self, tables: dict[str, HostTable],
                 stream_bytes: int = DEFAULT_STREAM_BYTES,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS,
                 float_dtype=None,
                 prefetch_depth: "int | None" = None):
        super().__init__(tables, float_dtype)
        self.stream_bytes = stream_bytes
        self.chunk_rows = chunk_rows
        # double-buffered phase-A prefetch depth (engine/pipeline_io.py;
        # 0 = the byte-identical serial loops). The scheduler may lower
        # it per query (governor depth admission, ladder relief entry)
        # through the same _restore contract as chunk_rows
        self.prefetch_depth = (pipeline_io.resolve_depth()
                               if prefetch_depth is None
                               else max(0, int(prefetch_depth)))
        # per-query prefetch attribution (wait billed to wall-clock,
        # hidden overlapped under compute), merged into the published
        # timings at result() by _ForwardResult
        self._pf_stats: dict = {}
        # (plan key) -> phase-B executor
        self._reduced: dict[object, _PhaseBExecutor] = {}
        # (table, filter repr) -> reduced HostTable, shared across plans
        self._survivor_cache: dict[tuple, HostTable] = {}

    def _note_prefetch(self, stats: dict) -> None:
        """Fold one prefetcher's close() stats into the query's
        attribution (several phase-A loops can run per query — one per
        streamed table plus the partial-agg chunk loop)."""
        if not stats or stats.get("depth", 0) <= 0:
            return
        pf = self._pf_stats
        pf["prefetch_wait_ms"] = (pf.get("prefetch_wait_ms", 0.0)
                                  + stats["wait_s"] * 1000.0)
        pf["prefetch_hidden_s"] = (pf.get("prefetch_hidden_s", 0.0)
                                   + stats["hidden_s"])
        pf["prefetch_depth"] = max(pf.get("prefetch_depth", 0),
                                   stats["depth"])

    def _is_streamed(self, table: str) -> bool:
        return _table_bytes(self.tables[table]) > self.stream_bytes

    # ----------------------------------------------------------------- API

    # chunk halving floor: below this the per-chunk dispatch overhead
    # dominates and an OOM is no longer a chunk-size problem
    MIN_CHUNK_ROWS = 1 << 12

    def execute_async(self, planned: P.PlannedQuery, key: object = None):
        from nds_tpu.sql import params as sqlparams
        if sqlparams.has_params(planned) and self._streamed_scans(planned):
            # the out-of-core phase machinery evaluates literals as
            # trace constants (keep masks, chunk-scan fingerprints):
            # streamed parameterized plans run their inlined form
            planned = sqlparams.inline(planned)
        scans = self._streamed_scans(planned)
        if not scans:
            # unstreamed: the base device path runs (natively
            # parameterized when the plan carries params)
            return super().execute_async(planned, key)
        key = key if key is not None else id(planned)
        # a failed streamed query must never inherit the previous
        # query's span OR timings (same reset contract as the base
        # executor; last_timings rebinds only after phase A succeeds)
        self.last_query_span = None
        self.last_timings = {}
        # fresh prefetch attribution window: phase A below may run
        # several prefetchers; their stats accumulate here and publish
        # at result() (a plan-cache-warm query that skips phase A
        # publishes nothing)
        self._pf_stats = {}
        # graceful degradation: an OOM-classified failure halves the
        # chunk size and rebuilds phase A before giving up — the
        # out-of-core engine's whole premise is that residency, not
        # total size, is the limit (no-sleep policy from the pipeline
        # module, the one place engine retry wiring is instantiated)
        from nds_tpu.engine.scheduler import adaptive_policy
        policy = adaptive_policy(3)
        last_attempt = policy.max_attempts - 1
        for attempt in policy.attempts():
            try:
                if key not in self._reduced:
                    sub = self._build_phase_b(planned, scans)
                    while len(self._reduced) >= self.MAX_REDUCED:
                        self._reduced.pop(next(iter(self._reduced)))
                    self._reduced[key] = sub
                sub = self._reduced[key]
                res = sub.execute_async(planned, key)
                break
            except Exception as exc:  # noqa: BLE001 - classified below
                if (not is_oom(exc) or attempt >= last_attempt
                        or self.chunk_rows // 2 < self.MIN_CHUNK_ROWS):
                    raise
                self.chunk_rows //= 2
                # drop every artifact sized by the old chunking
                self._reduced.pop(key, None)
                self._survivor_cache.clear()
                obs_metrics.counter("chunk_shrink_total").inc()
                from nds_tpu.utils.report import TaskFailureCollector
                TaskFailureCollector.notify(
                    f"OOM-classified failure in chunked execution "
                    f"({type(exc).__name__}); halving chunk_rows to "
                    f"{self.chunk_rows}")
        self.last_timings = sub.last_timings
        # the sub-executor's span/timings finalize at result(): forward
        # them so obs.query_timings(chunked_executor) sees the query
        return _ForwardResult(self, sub, res, pf=self._pf_stats)

    def _build_phase_b(self, planned: P.PlannedQuery, scans: dict):
        """Phase A (reduce streamed tables) + phase-B executor choice
        for one plan."""
        reduced = {}
        for table, table_scans in scans.items():
            reduced[table] = self._reduce_table(table, table_scans)
        sub = None
        # filters didn't shrink some table under the budget: try
        # per-chunk PARTIAL AGGREGATION before resorting to a full
        # upload (the q1 full-scan-aggregate shape)
        big = [t for t, r in reduced.items()
               if _table_bytes(r) > self.stream_bytes]
        if len(big) == 1 and len(scans[big[0]]) == 1:
            try:
                sub = self._try_partial_agg(
                    planned, big[0], scans[big[0]][0], reduced)
            except Exception as exc:  # noqa: BLE001 - fall back
                if isinstance(exc, QueryDeadlineExceeded):
                    # a deadlined query must ABORT, not fall back to a
                    # full upload that takes even longer
                    raise
                if (is_oom(exc)
                        and self.chunk_rows // 2 >= self.MIN_CHUNK_ROWS):
                    # the chunk-halving loop can still shrink phase A;
                    # once the floor is reached, OOM falls through to
                    # the full-upload fallback below like any other
                    # partial-agg failure
                    raise
                from nds_tpu.utils.report import TaskFailureCollector
                TaskFailureCollector.notify(
                    f"partial-agg path failed for {big[0]}, falling "
                    f"back to full upload: "
                    f"{type(exc).__name__}: {exc}")
        if sub is None:
            # identity reductions (keep-all) are the session's own
            # table objects — those buffers can live in the shared
            # pool; genuinely reduced tables differ per plan and
            # stay executor-local
            local = {t for t, r in reduced.items()
                     if r is not self.tables[t]}
            sub = _PhaseBExecutor({**self.tables, **reduced},
                                  self.float_dtype, self._buffers,
                                  local)
        return sub

    def _streamed_scans(self, planned: P.PlannedQuery) -> dict:
        """{table: [Scan, ...]} for streamed tables in this plan."""
        out: dict[str, list] = {}
        for root in [planned.root] + list(planned.scalar_subplans):
            for node in P.walk_plan(root):
                if isinstance(node, P.Scan) and self._is_streamed(
                        node.table):
                    out.setdefault(node.table, []).append(node)
        return out

    # -------------------------------------------- phase A: partial agg

    MERGEABLE = ("sum", "count", "min", "max", "avg")

    def _try_partial_agg(self, planned: P.PlannedQuery, table: str,
                         scan: P.Scan, reduced: dict):
        """Split the plan at the first Aggregate above the streamed
        scan: per-chunk partial aggregation on device, host concat of
        the (small) partials, then the ORIGINAL plan runs with the
        aggregate's subtree replaced by a merge over the partials.
        Returns a phase-B executor, or None when the plan shape does
        not split."""
        for sub in planned.scalar_subplans:
            for node in P.walk_plan(sub):
                if isinstance(node, P.Scan) and node.table == table:
                    return None  # scalar subplan scans the big table
        path = self._path_to(planned.root, scan)
        if path is None:
            return None
        agg_i = None
        for i in range(len(path) - 2, -1, -1):
            node = path[i]
            if isinstance(node, P.Aggregate):
                agg_i = i
                break
            if not isinstance(node, (P.Filter, P.Project, P.Join,
                                     P.SemiJoin, P.DerivedScan)):
                return None  # blocking op (sort/window/...) below any agg
            # chunking only distributes over sides whose rows partition
            # the operator's OUTPUT: either side of an inner join, the
            # LEFT of a left-outer or semi/anti join. Chunking a semi
            # join's RIGHT (the EXISTS set) would evaluate membership
            # against one chunk at a time — q22's NOT EXISTS(orders)
            # counted a customer once per orders-chunk without this.
            child = path[i + 1]
            if isinstance(node, P.SemiJoin) and child is not node.left:
                return None
            if isinstance(node, P.Join):
                if node.kind == "full":
                    return None  # distributes over neither side
                if node.kind != "inner" and child is not node.left:
                    return None
        if agg_i is None:
            return None
        A = path[agg_i]
        if any(spec.distinct or spec.func not in self.MERGEABLE
               for _n, spec in A.aggs):
            return None
        agg2, sum_dtypes = self._decompose(A)
        base = {**self.tables,
                **{t: r for t, r in reduced.items() if t != table}}
        planned_a = P.PlannedQuery(
            root=agg2, scalar_subplans=list(planned.scalar_subplans),
            column_names=[])
        plan_local = {t for t, r in reduced.items()
                      if r is not self.tables[t]} | {table}
        with get_tracer().span("chunk.partial_agg", table=table):
            parts = self._run_partial_chunks(base, reduced[table],
                                             table, planned_a,
                                             plan_local)
        ptable = self._partials_host_table(agg2, parts)
        pb = "__pa_scan__"
        scan_p = P.Scan(table=ptable.name, binding=pb,
                        output=list(agg2.output), filters=[])
        mg_keys = [(n, ir.ColRef(pb, n, e.dtype))
                   for n, e in A.group_keys]
        mg_aggs = []
        for n, spec in A.aggs:
            if spec.func == "avg":
                sdt = sum_dtypes[n]
                mg_aggs.append((n + "__s", P.AggSpec(
                    "sum", ir.ColRef(pb, n + "__s", sdt), False, sdt)))
                mg_aggs.append((n + "__c", P.AggSpec(
                    "sum", ir.ColRef(pb, n + "__c", INT64), False,
                    INT64)))
            elif spec.func == "count":
                mg_aggs.append((n, P.AggSpec(
                    "sum", ir.ColRef(pb, n, INT64), False, INT64)))
            else:  # sum / min / max merge with themselves
                mg_aggs.append((n, P.AggSpec(
                    spec.func, ir.ColRef(pb, n, spec.dtype), False,
                    spec.dtype)))
        merge_node = P.Aggregate(child=scan_p, group_keys=mg_keys,
                                 aggs=mg_aggs, binding="__pa_merge__")
        sub = _PartialAggExecutor(
            {**base, ptable.name: ptable}, self.float_dtype,
            self._buffers, plan_local | {ptable.name},
            {id(A): (merge_node, A, sum_dtypes)}, [merge_node])
        return sub

    @staticmethod
    def _path_to(root: P.Node, target: P.Node):
        if root is target:
            return [root]
        for c in P.children(root):
            p = ChunkedExecutor._path_to(c, target)
            if p is not None:
                return [root] + p
        return None

    @staticmethod
    def _decompose(A: P.Aggregate):
        """avg -> (sum, count) pair so partials merge exactly; other
        mergeable funcs keep their own spec. Returns (agg2, {avg name:
        sum dtype})."""
        aggs2, sum_dtypes = [], {}
        for n, spec in A.aggs:
            if spec.func != "avg":
                aggs2.append((n, spec))
                continue
            arg_dt = spec.arg.dtype
            if isinstance(arg_dt, (FloatType, DecimalType)):
                sdt = arg_dt
            else:
                sdt = INT64
            sum_dtypes[n] = sdt
            aggs2.append((n + "__s",
                          P.AggSpec("sum", spec.arg, False, sdt)))
            aggs2.append((n + "__c",
                          P.AggSpec("count", spec.arg, False, INT64)))
        agg2 = P.Aggregate(child=A.child, group_keys=list(A.group_keys),
                           aggs=aggs2, binding=A.binding)
        return agg2, sum_dtypes

    @staticmethod
    def _slice_table(t: HostTable, start: int, stop: int) -> HostTable:
        cols = {}
        for name, c in t.columns.items():
            cols[name] = HostColumn(
                c.dtype, c.values[start:stop], c.dictionary,
                None if c.null_mask is None
                else c.null_mask[start:stop])
        return HostTable(t.name, t.schema, cols)

    def _run_partial_chunks(self, base: dict, big: HostTable,
                            table: str, planned_a: P.PlannedQuery,
                            plan_local: set):
        """Execute the partial aggregate once per chunk. All full-size
        chunks share ONE compiled program (same static shape, buffers
        swapped per chunk); the tail chunk compiles once more at its
        own size."""
        n = big.nrows
        C = min(self.chunk_rows, max(n, 1))
        spans = [(s, min(s + C, n)) for s in range(0, n, C)]
        obs_metrics.counter("chunk_scans_total").inc(len(spans))
        by_size: dict[int, list] = {}
        for span in spans:
            by_size.setdefault(span[1] - span[0], []).append(span)
        # bounds of the table being chunked must come from ALL its rows:
        # the chunk program compiles ONCE from chunk 0's executor, and
        # col_bounds feed key packing clips, group capacity, and int32
        # narrowing — chunk-0-local bounds would silently corrupt later
        # chunks (clustered layouts make this the common case, not the
        # edge case)
        # ndslint: waive[NDS110] -- bounds-probe helper over one host table (col_bounds/col_is_sorted only); no plan ever executes on it
        bx = dx.DeviceExecutor({table: big})
        full_bounds = {(table, name): bx.col_bounds(table, name)
                       for name in big.columns}
        # same hazard for the presorted-build fast path: a chunk-0-local
        # "sorted" verdict would bake a sort-skip into the program later
        # chunks reuse with swapped (possibly unsorted) buffers — seed
        # the WHOLE-table verdict instead (a slice of a globally sorted
        # column is still sorted, so chunk reuse stays valid)
        full_bounds.update(
            {(table, name, "sorted"): bx.col_is_sorted(table, name)
             for name in big.columns})
        parts = []
        for size, group in by_size.items():
            # between-chunk control point: the per-query deadline is
            # enforced INSIDE the attempt (a 200-chunk scan must stop
            # at the next boundary, not finish a doomed pass), and the
            # heartbeat shows per-chunk liveness to the hang watchdog
            check_deadline()
            watchdog.beat("engine", phase="chunk.partial_agg",
                          table=table)
            s0, e0 = group[0]
            # every per-plan table (reduced variants + the chunked one)
            # stays executor-local; only immutable full tables share
            # the session pool
            ex = _PhaseBExecutor(
                {**base, table: self._slice_table(big, s0, e0)},
                self.float_dtype, self._buffers, plan_local)
            ex._bounds.update(full_bounds)
            # the swap loop below rebuilds this table's buffers as
            # RAW slices each chunk; an encoded chunk-0 program would
            # misread them, so the chunked table uploads raw (the
            # phase-A keep-mask scan is where streamed chunks scan
            # encoded)
            ex._no_encode = {table}
            parts.append(ex.execute(planned_a))  # compiles + runs chunk 0
            entry = ex._compiled[id(planned_a)]
            compiled, side = entry["compiled"], entry["side"]
            slack = entry["slack"]
            # the swap key template: exactly the streamed table's
            # buffer keys the compiled program consumes (raw uploads —
            # see _no_encode above), fixed after chunk 0's compile
            tmpl = set(ex._collect_buffers(planned_a))

            def _stage_swap(span):
                """Host half of one chunk: slice the streamed columns
                and issue their async host->device transfer
                (jax.device_put). Runs on the prefetch worker when
                depth > 0 — while the compiled program is still
                executing the previous chunk."""
                s, e = span
                swap = {}
                for name in big.columns:
                    bkey = f"{table}.{name}"
                    if bkey not in tmpl:
                        continue
                    col = big.columns[name]
                    swap[bkey] = jax.device_put(col.values[s:e])
                    if bkey + "#v" in tmpl:
                        swap[bkey + "#v"] = jax.device_put(
                            col.null_mask[s:e])
                return swap, sum(b.nbytes for b in swap.values())

            pf = pipeline_io.ChunkPrefetcher(
                group[1:], _stage_swap, self.prefetch_depth,
                table=table)
            try:
                for staged in pf:
                    s, e = staged.item
                    check_deadline()
                    watchdog.beat("engine", phase="chunk.partial_agg",
                                  table=table)
                    bufs = ex._collect_buffers(planned_a)
                    bufs.update(staged.payload)
                    # per-chunk memory window (obs/memwatch): the
                    # staged swap bytes are accounted by the
                    # prefetcher from stage to release; the shared
                    # pool references bracket the compute only —
                    # together the live set the serial loop accounted
                    win = sum(getattr(b, "nbytes", 0)
                              for k, b in bufs.items()
                              if k not in staged.payload)
                    memwatch.add_live(win)
                    try:
                        # overflow-retry on the shared policy
                        # (slack-doubling shape, no backoff sleep —
                        # same as dist_exec)
                        from nds_tpu.engine.scheduler import (
                            adaptive_policy,
                        )
                        overflow_policy = adaptive_policy(4)
                        for attempt in overflow_policy.attempts():
                            # per-dispatch cost billing: each chunk
                            # (and each overflow retry) bills its
                            # program's compiler cost once
                            obs_costs.record_program(
                                type(ex).__name__, compiled)
                            with jitsan.dispatch(type(ex).__name__):
                                row, outs, overflow = compiled(bufs)
                            # ndslint: waive[NDS117] -- sanctioned per-chunk sync point: the overflow verdict gates the slack-doubling retry, and the partials must land on host before the next chunk swaps buffers
                            row_h, outs_h, over_h = jax.device_get(
                                (row, outs, overflow))
                            if int(over_h) == 0:
                                break
                            if attempt == overflow_policy.max_attempts - 1:
                                raise dx.DeviceExecError(
                                    "partial-agg chunk overflow "
                                    "persisted")
                            # skewed chunk expands past the
                            # chunk-0-sized join capacity: double
                            # slack and recompile, same as the
                            # executor's own overflow-retry contract
                            from nds_tpu.utils.report import (
                                TaskFailureCollector,
                            )
                            slack *= 2
                            TaskFailureCollector.notify(
                                f"partial-agg chunk [{s}:{e}] "
                                f"overflow; recompiling with "
                                f"slack={slack}")
                            from nds_tpu.cache import aot as cache_aot
                            jitted, side = ex._compile(planned_a,
                                                       slack)
                            # ndsjit finding: this overflow recompile
                            # was invisible to the cost ledger — a
                            # warm run could recompile here and still
                            # report compiles == 0
                            obs_metrics.counter(
                                "recompiles_total").inc()
                            compiled = cache_aot.lower_and_compile(
                                jitted, bufs, kind="partial_agg_retry")
                    finally:
                        memwatch.sub_live(win)
                        staged.release()
                    parts.append(ex._materialize(planned_a, row_h,
                                                 outs_h, side))
            finally:
                self._note_prefetch(pf.close())
        return parts

    @staticmethod
    def _partials_host_table(agg2: P.Aggregate, parts) -> HostTable:
        names = [n for n, _dt in agg2.output]
        dtypes = [dt for _n, dt in agg2.output]
        fields = []
        cols = {}
        for i, (name, dt) in enumerate(zip(names, dtypes)):
            vals = np.concatenate([np.asarray(p.cols[i]) for p in parts])
            valid_parts = []
            any_valid = any(p.valids[i] is not None for p in parts)
            if any_valid:
                for p in parts:
                    v = p.valids[i]
                    valid_parts.append(
                        np.ones(len(p.cols[i]), dtype=bool)
                        if v is None else np.asarray(v))
                mask = np.concatenate(valid_parts)
            else:
                mask = None
            if isinstance(dt, StringType):
                codes, dictionary = encode_strings(vals.astype(str))
                cols[name] = HostColumn(dt, codes, dictionary, mask)
            else:
                cols[name] = HostColumn(dt, vals, None, mask)
            fields.append((name, dt, True))
        schema = Schema.of(*fields)
        return HostTable("__pa_partials__", schema, cols)

    # ------------------------------------------------- phase A: chunk scan

    def _reduce_table(self, table: str, scans: list) -> HostTable:
        t = self.tables[table]
        # one reduced table serves every scan of it in the plan: a row
        # survives if ANY scan's filter conjunction accepts it (each
        # scan re-applies its own filters in phase B)
        cache_key = (table, tuple(sorted(
            repr(s.filters) for s in scans)))
        hit = self._survivor_cache.get(cache_key)
        if hit is not None:
            return hit
        need_cols = sorted({name for s in scans for name, _ in s.output})
        with get_tracer().span("chunk.reduce", table=table,
                               rows=t.nrows):
            keep = self._chunk_keep_mask(table, scans, need_cols)
        if keep.all():
            # zero reduction (filterless scan / fallback): the original
            # table IS the result — no multi-GB host copy
            reduced = t
        else:
            idx = np.nonzero(keep)[0]
            cols = {}
            for name in t.columns:
                c = t.columns[name]
                cols[name] = HostColumn(
                    c.dtype, c.values[idx], c.dictionary,
                    None if c.null_mask is None else c.null_mask[idx])
            reduced = HostTable(table, t.schema, cols)
        # bounded like _reduced: host RAM for survivor copies must not
        # accumulate across a 99-query run (live phase-B executors keep
        # their own references; eviction only drops the shared entry)
        while len(self._survivor_cache) >= self.MAX_REDUCED:
            self._survivor_cache.pop(next(iter(self._survivor_cache)))
        self._survivor_cache[cache_key] = reduced
        return reduced

    def invalidate_tables(self, names) -> None:
        """Scoped DML invalidation for the out-of-core engine: beyond
        the base executor's buffers/bounds/scan-views, drop the mutated
        tables' survivor copies and every phase-B executor (they embed
        reduced snapshots; which tables each one streamed isn't
        recorded, so the conservative drop is the correct one — their
        compiled programs persist in the AOT cache and re-attach
        without recompiling)."""
        super().invalidate_tables(names)
        touched = set(names)
        for ck in [ck for ck in self._survivor_cache
                   if ck[0] in touched]:
            del self._survivor_cache[ck]
        self._reduced.clear()

    def _chunk_keep_mask(self, table: str, scans: list,
                         need_cols: list) -> np.ndarray:
        t = self.tables[table]
        n = t.nrows
        C = min(self.chunk_rows, max(n, 1))
        # delta deleted-row bitmask: DF_*-deleted rows never survive
        # phase A regardless of what the filters say
        from nds_tpu.columnar import delta
        live = delta.live_mask(t)
        # an EMPTY filter conjunction accepts every row: if any scan of
        # this table is filterless, no reduction is possible (the one
        # reduced table serves all scans of it in phase B) — beyond
        # excluding deleted rows
        if any(not s.filters for s in scans):
            return np.ones(n, dtype=bool) if live is None \
                else live.copy()

        # encoded chunk scans (nds_tpu/columnar/): bitpack-only, with
        # bounds from the WHOLE table, so every chunk of a column
        # shares one spec and the compiled chunk program is reused
        # unchanged across chunks (RLE would change shape per chunk)
        from nds_tpu import columnar
        chunk_specs: dict = {}
        if columnar.enabled() and self.COLUMNAR_UPLOAD:
            for cname in need_cols:
                spec = columnar.chunk_spec(
                    t.columns[cname], C, self.col_bounds(table, cname))
                if spec is not None:
                    chunk_specs[cname] = spec

        skipped: list = []

        # ndsjit: waive[NDSJ302] -- t is self.tables[table], content-stamped into the fingerprint via tables=; skipped is trace-time bookkeeping that never shapes the program (warm hits legitimately skip it, see _keep_mask_compiled)
        def fn(bufs, n_valid):
            from nds_tpu.columnar import device as columnar_dev
            base = jnp.arange(C, dtype=jnp.int32) < n_valid
            keep = jnp.zeros(C, dtype=bool)
            for scan in scans:
                tr = dx._Trace(self, bufs)
                ctx = DCtx(C, base)
                for name, _dt in scan.output:
                    col = t.columns[name]
                    lo, hi = self.col_bounds(table, name)
                    sdict = col.dictionary if col.is_string else None
                    spec = chunk_specs.get(name)
                    if spec is not None:
                        arr, valid = columnar_dev.decode(
                            spec, bufs, name)
                    else:
                        arr, valid = bufs[name], bufs.get(name + "#v")
                    ctx.cols[(scan.binding, name)] = DVal(
                        arr, valid, sdict, lo, hi)
                for pred in scan.filters:
                    # PER-PREDICATE fallback: a filter the chunk
                    # program cannot evaluate (e.g. it references a
                    # scalar-subquery result, q32/q92 shape) is simply
                    # skipped — the other predicates (date ranges!)
                    # still reduce, and phase B re-applies everything
                    try:
                        ctx = tr._apply_filter(ctx, pred)
                    except Exception as exc:  # noqa: BLE001
                        skipped.append((pred, exc))
                keep = keep | ctx.row
            return keep

        def _stage_chunk(span):
            """Host half of one scan chunk: slice, pad the tail to the
            static shape, columnar-encode (pure numpy), and issue the
            async host->device transfer. Runs on the prefetch worker
            when depth > 0, overlapping the compiled keep-mask program
            still scanning the previous chunk."""
            start, stop = span
            bufs = {}
            for name in need_cols:
                col = t.columns[name]
                sl = col.values[start:stop]
                m = (None if col.null_mask is None
                     else col.null_mask[start:stop])
                if stop - start < C:  # tail: pad to the chunk shape
                    pad = C - (stop - start)
                    sl = np.concatenate(
                        [sl, np.zeros(pad, dtype=sl.dtype)])
                    if m is not None:
                        m = np.concatenate(
                            [m, np.zeros(pad, dtype=bool)])
                spec = chunk_specs.get(name)
                if spec is not None:
                    # every chunk encodes with the shared full-bounds
                    # spec: shapes stay static, so the one compiled
                    # program serves all chunks (the padded tail past
                    # nrows clips freely)
                    for sfx, arr in columnar.encode_values(
                            spec, sl, m, nrows=stop - start).items():
                        bufs[name + sfx] = jax.device_put(arr)
                    continue
                bufs[name] = jax.device_put(sl)
                if m is not None:
                    bufs[name + "#v"] = jax.device_put(m)
            return bufs, sum(b.nbytes for b in bufs.values())

        chunk_spans = [(start, min(start + C, n))
                       for start in range(0, n, C)]
        pf = pipeline_io.ChunkPrefetcher(
            chunk_spans, _stage_chunk, self.prefetch_depth, table=table)
        try:
            compiled = None
            keep_np = np.empty(n, dtype=bool)
            for staged in pf:
                start, stop = staged.item
                # same between-chunk control point as the partial-agg
                # loop: deadline stops a doomed scan at the next chunk,
                # the beat keeps the watchdog fed during long scans
                check_deadline()
                watchdog.beat("engine", phase="chunk.scan", table=table)
                obs_metrics.counter("chunk_scans_total").inc()
                bufs = staged.payload
                try:
                    if compiled is None:
                        # every chunk shares one static shape (the tail
                        # pads): AOT-compile once on the first chunk's
                        # buffers, consulting the persistent plan cache
                        # so a warm process scans with zero compiles
                        compiled = self._keep_mask_compiled(
                            table, scans, need_cols, C, fn, bufs,
                            chunk_specs)
                    obs_costs.record_program("chunkscan", compiled)
                    # the chunk-length scalar stages BEFORE the
                    # dispatch scope: its tiny h2d is control-plane,
                    # not a buffer leaking into the guarded hot path
                    nchunk = jnp.int32(stop - start)
                    with jitsan.dispatch("chunkscan"):
                        mask_d = compiled(bufs, nchunk)
                    with jitsan.declared("keep-mask readback"):
                        # sanctioned per-chunk sync point: the keep
                        # mask IS phase A's product and must land on
                        # host before the survivor gather
                        keep_np[start:stop] = np.asarray(  # ndsjit: waive[NDSJ303] -- the declared() scope above attributes this sync; it is phase A's product, not a hidden stall
                            mask_d)[:stop - start]
                finally:
                    staged.release()
            if skipped:
                from nds_tpu.utils.report import TaskFailureCollector
                TaskFailureCollector.notify(
                    f"chunked scan of {table}: {len(skipped)} filter(s) "
                    f"not chunk-evaluable, re-applied in phase B only "
                    f"({type(skipped[0][1]).__name__})")
            return keep_np if live is None else keep_np & live
        except Exception as exc:  # noqa: BLE001 - conservative fallback
            if isinstance(exc, QueryDeadlineExceeded):
                # deadlined queries abort; "keep all rows" would turn a
                # timeout into an even slower full-table phase B
                raise
            from nds_tpu.resilience.retry import TRANSIENT, classify
            if classify(exc) == TRANSIENT:
                # classified transients (injected faults, OOM) PROPAGATE
                # instead of degrading: the executor's chunk-halving
                # loop handles the OOMs and the pipeline's retry policy
                # re-runs the rest — retry semantics identical whether
                # the staging ran inline or on the prefetch worker. The
                # keep-all fallback would silently trade a retryable
                # hiccup for a full-table phase B.
                raise
            from nds_tpu.utils.report import TaskFailureCollector
            obs_metrics.counter("chunk_fallbacks_total").inc()
            TaskFailureCollector.notify(
                f"chunked scan fell back to full rows for {table}: "
                f"{type(exc).__name__}: {exc}")
            return np.ones(n, dtype=bool) if live is None \
                else live.copy()
        finally:
            # cancel-at-chunk-boundary + unconsumed-buffer release on
            # every exit path (success, fallback, deadline abort, drain)
            self._note_prefetch(pf.close())

    def _keep_mask_compiled(self, table: str, scans: list,
                            need_cols: list, C: int, fn, bufs: dict,
                            chunk_specs: "dict | None" = None):
        """AOT form of the phase-A chunk-scan program, consulted
        against the persistent plan cache (kind ``chunkscan``): the
        fingerprint folds in the scans' filter trees (extra roots),
        the streamed table's content stamp, the chunk shape, and the
        compute dtype. A warm hit skips the trace entirely — which
        also skips the per-predicate ``skipped`` bookkeeping, matching
        the baked behavior of the program it restores."""
        from nds_tpu.cache import aot as cache_aot
        from nds_tpu.engine import kernels as KX
        pc, fp = cache_aot.try_fingerprint(
            "chunkscan",
            {"table": table, "chunk": C, "cols": tuple(need_cols),
             "float_dtype": str(self.float_dtype),
             "donate": KX.donate_enabled(),
             # per-column chunk encodings shape the program (packed
             # word shapes, fused decode); specs are deterministic
             # from content+mode but the explicit fold keeps the key
             # honest even if that ever changes
             "enc": tuple(sorted((n, repr(s)) for n, s in
                          (chunk_specs or {}).items()))},
            tables=self.tables, extra_roots=list(scans))
        # chunk buffers are rebuilt per chunk and used exactly once:
        # donating them halves the phase-A device residency (the keep
        # mask no longer double-buffers against the chunk it scans)
        KX.silence_donation_warnings()
        compiled, _extra, _hit = cache_aot.cached_compile(
            pc, fp, "chunkscan", lambda: KX.donate_jit(fn, (0,)),
            (bufs, jnp.int32(0)))
        return compiled


def make_chunked_factory(stream_bytes: int = DEFAULT_STREAM_BYTES,
                         chunk_rows: int = DEFAULT_CHUNK_ROWS,
                         precision: str = "f64",
                         prefetch_depth: "int | None" = None):
    """Session executor factory (make_device_factory analog) for the
    out-of-core engine."""
    if precision not in dx.PRECISIONS:
        raise ValueError(f"unknown engine.precision {precision!r}")
    name = dx.PRECISIONS[precision]
    float_dtype = None if name is None else getattr(jnp, name)
    holder: dict = {}

    def factory(tables):
        ex = holder.get("ex")
        if ex is None or ex.tables is not tables:
            ex = ChunkedExecutor(tables, stream_bytes, chunk_rows,
                                 float_dtype,
                                 prefetch_depth=prefetch_depth)
            holder["ex"] = ex
        return ex

    factory.invalidate = holder.clear

    def invalidate_tables(names):
        ex = holder.get("ex")
        if ex is not None:
            ex.invalidate_tables(names)

    factory.invalidate_tables = invalidate_tables
    return factory
